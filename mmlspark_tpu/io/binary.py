"""Binary file ingestion.

Parity: ``io/binary/BinaryFileFormat.scala`` (252 LoC Spark datasource
yielding ``(path, bytes)`` rows, with recursive traversal, zip-file
expansion, and subsampling) and ``BinaryFileReader.scala:105`` —
rebuilt as DataFrame constructors instead of a lazy file format.
"""

from __future__ import annotations

import fnmatch
import os
import zipfile
from typing import List, Optional, Tuple

import numpy as np

from ..core.dataframe import DataFrame, object_col

__all__ = ["list_binary_files", "read_binary_files"]


def list_binary_files(path: str, recursive: bool = True,
                      pattern: Optional[str] = None) -> List[str]:
    if os.path.isfile(path):
        return [path]
    out: List[str] = []
    if recursive:
        for root, _dirs, files in os.walk(path):
            for f in sorted(files):
                out.append(os.path.join(root, f))
    else:
        out = [os.path.join(path, f) for f in sorted(os.listdir(path))
               if os.path.isfile(os.path.join(path, f))]
    if pattern:
        out = [p for p in out if fnmatch.fnmatch(os.path.basename(p), pattern)]
    return out


def _read_one(path: str, inspect_zip: bool) -> List[Tuple[str, bytes]]:
    if inspect_zip and path.endswith(".zip") and zipfile.is_zipfile(path):
        rows = []
        with zipfile.ZipFile(path) as zf:
            for name in zf.namelist():
                if not name.endswith("/"):
                    rows.append((f"{path}/{name}", zf.read(name)))
        return rows
    with open(path, "rb") as f:
        return [(path, f.read())]


def read_binary_files(path: str, recursive: bool = True,
                      pattern: Optional[str] = None,
                      sample_ratio: float = 1.0, seed: int = 0,
                      inspect_zip: bool = True,
                      npartitions: int = 1) -> DataFrame:
    """Directory/file/zip → DataFrame with ``path`` and ``bytes`` columns."""
    files = list_binary_files(path, recursive, pattern)
    if sample_ratio < 1.0:
        rng = np.random.default_rng(seed)
        files = [f for f in files if rng.random() < sample_ratio]
    rows: List[Tuple[str, bytes]] = []
    for f in files:
        rows.extend(_read_one(f, inspect_zip))
    return DataFrame({"path": object_col([r[0] for r in rows]),
                      "bytes": object_col([r[1] for r in rows])},
                     npartitions=npartitions)
