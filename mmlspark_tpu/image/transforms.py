"""Pipelined image ops with the reference's stage-map algebra.

Parity: ``opencv/.../ImageTransformer.scala:28-280`` — each op is a
``{"action": name, ...params}`` dict; the transformer applies the list in
order. Op names, parameter keys, and semantics match the reference exactly
(``resize`` incl. shorter-side ``size``+``keepAspectRatio``, ``crop``,
``centercrop``, ``colorformat``, ``blur``, ``threshold``, ``gaussiankernel``,
``flip``), backed by the same native OpenCV (cv2) the reference reaches via
JNI. Optional tensor output (CHW float with scale/mean/std normalization)
matches the main class at ``ImageTransformer.scala:417+``.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Optional

import numpy as np

from ..core.dataframe import DataFrame, object_col
from ..core.params import HasInputCol, HasOutputCol, Param
from ..core.pipeline import Transformer
from .schema import ImageSchema, decode_image, make_image

__all__ = ["ImageTransformer", "ResizeImage", "CropImage", "CenterCropImage",
           "ColorFormat", "Blur", "Threshold", "GaussianKernel", "Flip",
           "normalize_program"]


def _cv2():
    import cv2
    return cv2

# -- op implementations (image: HWC uint8 ndarray → ndarray) -----------------

def _apply_resize(img: np.ndarray, p: dict) -> np.ndarray:
    cv2 = _cv2()
    if "size" in p:
        size = int(p["size"])
        if p.get("keepAspectRatio", False):
            h, w = img.shape[:2]
            ratio = size / min(h, w)
            tw, th = int(round(ratio * w)), int(round(ratio * h))
            return cv2.resize(img, (tw, th))
        return cv2.resize(img, (size, size))
    return cv2.resize(img, (int(p["width"]), int(p["height"])))


def _apply_crop(img: np.ndarray, p: dict) -> np.ndarray:
    x, y = int(p["x"]), int(p["y"])
    h, w = int(p["height"]), int(p["width"])
    return img[y:y + h, x:x + w]


def _apply_centercrop(img: np.ndarray, p: dict) -> np.ndarray:
    h, w = int(p["height"]), int(p["width"])
    ih, iw = img.shape[:2]
    y = max(0, (ih - h) // 2)
    x = max(0, (iw - w) // 2)
    return img[y:y + h, x:x + w]


def _apply_colorformat(img: np.ndarray, p: dict) -> np.ndarray:
    return _cv2().cvtColor(img, int(p["format"]))


def _apply_blur(img: np.ndarray, p: dict) -> np.ndarray:
    return _cv2().blur(img, (int(p["width"]), int(p["height"])))


def _apply_threshold(img: np.ndarray, p: dict) -> np.ndarray:
    _, out = _cv2().threshold(img, float(p["threshold"]), float(p["maxVal"]),
                              int(p["type"]))
    return out


def _apply_gaussiankernel(img: np.ndarray, p: dict) -> np.ndarray:
    cv2 = _cv2()
    kernel = cv2.getGaussianKernel(int(p["apertureSize"]), float(p["sigma"]))
    return cv2.filter2D(img, -1, kernel)


def _apply_flip(img: np.ndarray, p: dict) -> np.ndarray:
    return _cv2().flip(img, int(p["flipCode"]))


_OPS: Dict[str, Callable[[np.ndarray, dict], np.ndarray]] = {
    "resize": _apply_resize,
    "crop": _apply_crop,
    "centercrop": _apply_centercrop,
    "colorformat": _apply_colorformat,
    "blur": _apply_blur,
    "threshold": _apply_threshold,
    "gaussiankernel": _apply_gaussiankernel,
    "flip": _apply_flip,
}


# -- stage-dict constructors (mirror the reference's companion objects) ------

def ResizeImage(height: Optional[int] = None, width: Optional[int] = None,
                size: Optional[int] = None,
                keep_aspect_ratio: bool = False) -> dict:
    if size is not None:
        return {"action": "resize", "size": size,
                "keepAspectRatio": keep_aspect_ratio}
    return {"action": "resize", "height": height, "width": width}


def CropImage(x: int, y: int, height: int, width: int) -> dict:
    return {"action": "crop", "x": x, "y": y, "height": height, "width": width}


def CenterCropImage(height: int, width: int) -> dict:
    return {"action": "centercrop", "height": height, "width": width}


def ColorFormat(format: int) -> dict:
    return {"action": "colorformat", "format": format}


def Blur(height: int, width: int) -> dict:
    return {"action": "blur", "height": height, "width": width}


def Threshold(threshold: float, max_val: float, threshold_type: int = 0) -> dict:
    return {"action": "threshold", "threshold": threshold, "maxVal": max_val,
            "type": threshold_type}


def GaussianKernel(aperture_size: int, sigma: float) -> dict:
    return {"action": "gaussiankernel", "apertureSize": aperture_size,
            "sigma": sigma}


class Flip:
    FLIP_UP_DOWN = 0
    FLIP_LEFT_RIGHT = 1
    FLIP_BOTH = -1

    def __new__(cls, flip_code: int = 1) -> dict:  # type: ignore[misc]
        return {"action": "flip", "flipCode": flip_code}


@functools.lru_cache(maxsize=None)
def normalize_program(scale: float, mean: Optional[tuple],
                      std: Optional[tuple], channels: int,
                      bgr_to_rgb: bool = True):
    """The jitted on-device half of the tensor path: dense ``(N, H, W, C)``
    **uint8** batch in, normalized float32 ``(N, C, H, W)`` batch out.

    Same math as the host tensor branch of :class:`ImageTransformer`
    (scale, BGR→RGB flip, mean/std), but it runs AFTER the h2d transfer —
    so the wire carries one byte per pixel-channel instead of four. The
    cache key is the normalization config, so steady state reuses one
    compiled program per transformer configuration."""
    import jax
    import jax.numpy as jnp

    perm = ([2, 1, 0] + list(range(3, channels))
            if bgr_to_rgb and channels >= 3 else list(range(channels)))
    mean_t = None if mean is None else np.asarray(mean, np.float32)
    std_t = None if std is None else np.asarray(std, np.float32)

    def _norm(x):
        y = x.astype(jnp.float32) * jnp.float32(scale)
        y = y[..., jnp.asarray(perm)]
        if mean_t is not None:
            y = y - mean_t
        if std_t is not None:
            y = y / std_t
        return jnp.transpose(y, (0, 3, 1, 2))

    return jax.jit(_norm)


def _as_key(v) -> Optional[tuple]:
    if v is None:
        return None
    arr = np.asarray(v, np.float32).reshape(-1)
    return tuple(float(x) for x in arr)


class ImageTransformer(Transformer, HasInputCol, HasOutputCol):
    """Apply a list of image ops; optionally emit a normalized float tensor.

    ``stages`` is the JSON-able op list, so the whole configuration
    round-trips through save/load like the reference's param map.
    """

    stages = Param((list, dict), default=[], doc="ordered op dicts "
                   "({'action': name, ...}), reference stage-map algebra")
    to_tensor = Param(bool, default=False,
                      doc="emit CHW float32 tensor instead of an image struct")
    color_scale_factor = Param(float, default=1.0 / 255.0,
                               doc="scalar multiplier before mean/std")
    normalize_mean = Param((list, float), default=None,
                           doc="per-channel mean (RGB order) for tensor output")
    normalize_std = Param((list, float), default=None,
                          doc="per-channel std (RGB order) for tensor output")

    def __init__(self, **kw):
        super().__init__(**kw)
        self._set_default(input_col="image", output_col="image")

    # fluent builders (reference test DSL: ImageTransformer().resize(...)...)
    def _add(self, stage: dict) -> "ImageTransformer":
        self.set(stages=self.get("stages") + [stage])
        return self

    def resize(self, height=None, width=None, size=None,
               keep_aspect_ratio=False):
        return self._add(ResizeImage(height, width, size, keep_aspect_ratio))

    def crop(self, x, y, height, width):
        return self._add(CropImage(x, y, height, width))

    def center_crop(self, height, width):
        return self._add(CenterCropImage(height, width))

    def color_format(self, format):
        return self._add(ColorFormat(format))

    def blur(self, height, width):
        return self._add(Blur(height, width))

    def threshold(self, threshold, max_val, threshold_type=0):
        return self._add(Threshold(threshold, max_val, threshold_type))

    def gaussian_kernel(self, aperture_size, sigma):
        return self._add(GaussianKernel(aperture_size, sigma))

    def flip(self, flip_code=1):
        return self._add(Flip(flip_code))

    # -- execution -----------------------------------------------------------
    def _apply_one(self, cell):
        if cell is None:
            return None
        if isinstance(cell, (bytes, bytearray)):
            struct = decode_image(bytes(cell))
            if struct is None:
                return None
            img = struct["data"]
            origin = struct["origin"]
        elif ImageSchema.is_image(cell):
            img = np.asarray(cell["data"], dtype=np.uint8)
            origin = cell.get("origin", "")
        else:
            img = np.asarray(cell, dtype=np.uint8)
            origin = ""
        for stage in self.get("stages"):
            op = _OPS.get(stage["action"])
            if op is None:
                raise ValueError(f"unsupported transformation {stage['action']!r}")
            img = op(img, stage)
            if img.ndim == 2:
                img = img[:, :, None]
        if self.get("to_tensor"):
            x = img.astype(np.float32) * np.float32(self.get("color_scale_factor"))
            mean, std = self.get_or_none("normalize_mean"), self.get_or_none("normalize_std")
            # reference normalizes in RGB order on a BGR image; flip channels
            if x.shape[-1] >= 3:
                x = x[:, :, [2, 1, 0] + list(range(3, x.shape[-1]))]
            if mean is not None:
                x = x - np.asarray(mean, np.float32)
            if std is not None:
                x = x / np.asarray(std, np.float32)
            return np.ascontiguousarray(np.transpose(x, (2, 0, 1)))  # CHW
        return make_image(img, origin)

    def _transform(self, df: DataFrame) -> DataFrame:
        col = df[self.get("input_col")]
        return df.with_column(self.get("output_col"),
                              object_col([self._apply_one(c) for c in col]))

    # -- dense uint8 device ingest -------------------------------------------
    def _apply_uint8(self, cell) -> Optional[np.ndarray]:
        """The host half of :meth:`transform_resident`: decode + cv2 stages
        only, staying HWC **uint8** end to end (no float cast, no
        normalize — that happens on device, after the transfer)."""
        if cell is None:
            return None
        if isinstance(cell, (bytes, bytearray)):
            struct = decode_image(bytes(cell))
            if struct is None:
                return None
            img = struct["data"]
        elif ImageSchema.is_image(cell):
            img = np.asarray(cell["data"], dtype=np.uint8)
        else:
            img = np.asarray(cell, dtype=np.uint8)
        for stage in self.get("stages"):
            op = _OPS.get(stage["action"])
            if op is None:
                raise ValueError(
                    f"unsupported transformation {stage['action']!r}")
            img = op(img, stage)
            if img.ndim == 2:
                img = img[:, :, None]
        return np.ascontiguousarray(img, dtype=np.uint8)

    def transform_resident(self, df: DataFrame,
                           slab_pool=None) -> DataFrame:
        """Dense-uint8 device tensor path: cv2 stages on the host (uint8
        throughout), ONE counted ingest h2d of the dense ``(N, H, W, C)``
        uint8 batch, then the jitted :func:`normalize_program` turns it
        into the normalized float32 CHW tensor ON DEVICE.

        Versus staging the host-normalized float32 tensor, the wire moves
        4x fewer bytes for the same resident result — the
        ``mmlspark_residency_h2d_bytes_total{site="ingest"}`` counter is
        the proof, and the tests pin it. The output column lands device-
        born via :meth:`DataFrame.with_device_column` (its host side is a
        lazy mirror; no d2h until someone materializes it).

        Requires the stage list to produce one uniform image shape (a
        ``resize``/``crop``/``centercrop`` stage in the list); raises
        ``ValueError`` otherwise. ``slab_pool`` (a
        :class:`~mmlspark_tpu.models.runner.StagingSlabPool`) makes the
        dense host batch a reusable pre-touched uint8 slab so the async
        put streams from warm pages."""
        from ..core.residency import DeviceColumn
        cells = [self._apply_uint8(c) for c in df[self.get("input_col")]]
        imgs = [c for c in cells if c is not None]
        if not imgs:
            raise ValueError("transform_resident: no decodable images")
        shape = imgs[0].shape
        if any(i.shape != shape for i in imgs):
            raise ValueError(
                "transform_resident needs a uniform output shape — add a "
                f"resize/crop stage (saw {sorted({i.shape for i in imgs})})")
        if any(c is None for c in cells):
            raise ValueError("transform_resident: null image cells")
        n = len(cells)
        if slab_pool is not None:
            slab = slab_pool.acquire((n,) + shape, np.uint8)
        else:
            slab = np.empty((n,) + shape, np.uint8)
        for i, img in enumerate(cells):
            slab[i] = img
        # counted: ONE site="ingest" h2d of n*H*W*C uint8 bytes
        dense = DeviceColumn.from_host(slab, df.partition_bounds())
        prog = normalize_program(
            float(self.get("color_scale_factor")),
            _as_key(self.get_or_none("normalize_mean")),
            _as_key(self.get_or_none("normalize_std")),
            int(shape[-1]))
        # device-born: no transfer, no count
        chunks = [prog(chunk) for chunk in dense.device_chunks()]
        if slab_pool is not None:
            # the CPU backend may alias the numpy buffer into the "device"
            # array — only recycle the slab once the normalized outputs
            # (which read through it) are materialized
            import jax
            jax.block_until_ready(chunks)
            slab_pool.release(slab)
        out = DeviceColumn.from_device(chunks)
        return df.with_device_column(self.get("output_col"), out)
