"""Unroll/resize stages (the opencv-free JVM path of the reference).

Parity: ``core/.../image/UnrollImage.scala:31-152`` (HWC uint8 image →
flat CHW double vector, with ``roll`` inverse), ``UnrollBinaryImage:187``
(decode+resize+unroll straight from compressed bytes), and
``ResizeImageTransformer.scala:59`` (resize without the OpenCV module).
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame, object_col
from ..core.params import HasInputCol, HasOutputCol, Param
from ..core.pipeline import Transformer
from .schema import decode_image, make_image

__all__ = ["unroll", "roll", "UnrollImage", "UnrollBinaryImage",
           "ResizeImageTransformer"]


def unroll(image: dict) -> np.ndarray:
    """HWC uint8 → flat float64 vector in CHW order
    (parity: ``UnrollImage.unroll:31-56``)."""
    data = np.asarray(image["data"], dtype=np.uint8)
    return np.transpose(data, (2, 0, 1)).astype(np.float64).ravel()


def roll(values: np.ndarray, like: dict) -> dict:
    """Inverse of :func:`unroll` (parity: ``UnrollImage.roll:58-127``)."""
    h, w, c = like["height"], like["width"], like["nChannels"]
    arr = np.clip(np.round(np.asarray(values, np.float64)), 0, 255)
    chw = arr.reshape(c, h, w).astype(np.uint8)
    return make_image(np.transpose(chw, (1, 2, 0)), like.get("origin", ""))


def _resize(img: np.ndarray, height: int, width: int) -> np.ndarray:
    try:
        import cv2
        out = cv2.resize(img, (width, height))
    except ImportError:
        from PIL import Image
        c = img.shape[-1]
        if c == 1:
            rgbish = img[:, :, 0]
        elif c == 4:  # BGRA → RGBA for PIL, keep all 4 channels
            rgbish = img[:, :, [2, 1, 0, 3]]
        else:
            rgbish = img[:, :, ::-1]  # BGR → RGB
        out = np.asarray(Image.fromarray(rgbish).resize((width, height)))
        if out.ndim == 3:  # undo the channel swap
            out = out[:, :, [2, 1, 0, 3]] if c == 4 else out[:, :, ::-1]
    return out[:, :, None] if out.ndim == 2 else out


class UnrollImage(Transformer, HasInputCol, HasOutputCol):
    """Image struct column → flat float vector column."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self._set_default(input_col="image", output_col="<image>")

    def _transform(self, df: DataFrame) -> DataFrame:
        col = df[self.get("input_col")]
        return df.with_column(
            self.get("output_col"),
            object_col([None if c is None else unroll(c) for c in col]))


class UnrollBinaryImage(Transformer, HasInputCol, HasOutputCol):
    """Compressed bytes column → decode (+optional resize) → flat vector
    (parity: ``UnrollBinaryImage:187``, ``unrollBytes:129-150``)."""

    height = Param(int, default=None, doc="resize height (optional)")
    width = Param(int, default=None, doc="resize width (optional)")

    def __init__(self, **kw):
        super().__init__(**kw)
        self._set_default(input_col="image", output_col="<image>")

    def _transform(self, df: DataFrame) -> DataFrame:
        h, w = self.get_or_none("height"), self.get_or_none("width")
        out = []
        for c in df[self.get("input_col")]:
            if c is None:
                out.append(None)
                continue
            img = decode_image(bytes(c)) if isinstance(c, (bytes, bytearray)) else c
            if img is None:
                out.append(None)
                continue
            data = img["data"]
            if h is not None and w is not None:
                data = _resize(data, h, w)
            out.append(unroll(make_image(data, img.get("origin", ""))))
        return df.with_column(self.get("output_col"), object_col(out))


class ResizeImageTransformer(Transformer, HasInputCol, HasOutputCol):
    """Resize image structs (parity: ``ResizeImageTransformer.scala:59``)."""

    height = Param(int, doc="target height")
    width = Param(int, doc="target width")

    def __init__(self, **kw):
        super().__init__(**kw)
        self._set_default(input_col="image", output_col="image")

    def _transform(self, df: DataFrame) -> DataFrame:
        h, w = self.get("height"), self.get("width")
        out = []
        for c in df[self.get("input_col")]:
            if c is None:
                out.append(None)
                continue
            if isinstance(c, (bytes, bytearray)):
                c = decode_image(bytes(c))
                if c is None:
                    out.append(None)
                    continue
            out.append(make_image(_resize(np.asarray(c["data"], np.uint8), h, w),
                                  c.get("origin", "")))
        return df.with_column(self.get("output_col"), object_col(out))
