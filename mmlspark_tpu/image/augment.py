"""Flip-based image-set augmentation.

Parity: ``opencv/.../ImageSetAugmenter.scala`` — emits the original rows
plus optional left-right / up-down flipped copies (doubling/tripling the
dataset for training).
"""

from __future__ import annotations

from ..core.dataframe import DataFrame, concat
from ..core.params import HasInputCol, HasOutputCol, Param
from ..core.pipeline import Transformer
from .transforms import Flip, ImageTransformer

__all__ = ["ImageSetAugmenter"]


class ImageSetAugmenter(Transformer, HasInputCol, HasOutputCol):
    flip_left_right = Param(bool, default=True, doc="add LR-flipped copies")
    flip_up_down = Param(bool, default=False, doc="add UD-flipped copies")

    def __init__(self, **kw):
        super().__init__(**kw)
        self._set_default(input_col="image", output_col="image")

    def _transform(self, df: DataFrame) -> DataFrame:
        ic, oc = self.get("input_col"), self.get("output_col")
        base = df.with_column(oc, df[ic]) if oc != ic else df
        parts = [base]
        if self.get("flip_left_right"):
            t = ImageTransformer(input_col=ic, output_col=oc,
                                 stages=[Flip(Flip.FLIP_LEFT_RIGHT)])
            parts.append(t.transform(df))
        if self.get("flip_up_down"):
            t = ImageTransformer(input_col=ic, output_col=oc,
                                 stages=[Flip(Flip.FLIP_UP_DOWN)])
            parts.append(t.transform(df))
        return concat(parts, npartitions=df.npartitions)
