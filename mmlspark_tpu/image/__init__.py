"""Image pipeline stages.

Parity surface: the reference's ``opencv`` module
(``opencv/.../ImageTransformer.scala``, ``ImageSetAugmenter.scala``) and the
JVM-side image helpers in core
(``image/UnrollImage.scala``, ``image/ResizeImageTransformer.scala``).

TPU-first framing: decode/resize/crop are host-side preprocessing on
uint8 HWC arrays (cv2 — the same native OpenCV the reference reaches via
JNI); normalization to CHW/NHWC float tensors is the device-feed boundary
and is vectorized per batch so ``device_put`` sees one contiguous array.
"""

from .schema import (ImageSchema, decode_image, encode_image, make_image,
                     to_nchw_tensor, to_nhwc_tensor)
from .transforms import (Blur, CenterCropImage, ColorFormat, CropImage, Flip,
                         GaussianKernel, ImageTransformer, ResizeImage,
                         Threshold)
from .unroll import ResizeImageTransformer, UnrollBinaryImage, UnrollImage
from .augment import ImageSetAugmenter

__all__ = [
    "ImageSchema", "make_image", "decode_image", "encode_image",
    "to_nchw_tensor", "to_nhwc_tensor", "ImageTransformer", "ResizeImage",
    "CropImage", "CenterCropImage", "ColorFormat", "Blur", "Threshold",
    "GaussianKernel", "Flip", "UnrollImage", "UnrollBinaryImage",
    "ResizeImageTransformer", "ImageSetAugmenter",
]
