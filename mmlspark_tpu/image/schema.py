"""Image struct schema + codecs.

Parity: Spark's ``ImageSchema`` rows (origin/height/width/nChannels/mode/data)
used throughout the reference (``core/.../core/schema/ImageSchemaUtils``,
``io/image/ImageUtils.scala``). An image cell here is a dict:

    {"origin": str, "height": int, "width": int, "nChannels": int,
     "mode": int, "data": np.uint8 HWC array (BGR channel order)}

BGR matches OpenCV/Spark so the stage algebra behaves identically.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["ImageSchema", "make_image", "decode_image", "encode_image",
           "to_nchw_tensor", "to_nhwc_tensor"]


class ImageSchema:
    """Mode constants (subset of OpenCV type codes Spark uses)."""
    OCV_8UC1 = 0
    OCV_8UC3 = 16
    OCV_8UC4 = 24

    FIELDS = ("origin", "height", "width", "nChannels", "mode", "data")

    @staticmethod
    def is_image(value) -> bool:
        return isinstance(value, dict) and {"height", "width", "data"} <= set(value)


def make_image(data: np.ndarray, origin: str = "") -> dict:
    """Wrap an HWC uint8 array (BGR) as an image struct."""
    data = np.asarray(data, dtype=np.uint8)
    if data.ndim == 2:
        data = data[:, :, None]
    h, w, c = data.shape
    mode = {1: ImageSchema.OCV_8UC1, 3: ImageSchema.OCV_8UC3,
            4: ImageSchema.OCV_8UC4}.get(c, ImageSchema.OCV_8UC3)
    return {"origin": origin, "height": h, "width": w, "nChannels": c,
            "mode": mode, "data": data}


def decode_image(raw: bytes, origin: str = "") -> Optional[dict]:
    """Compressed bytes → image struct (parity: ``ImageTransformer.decodeImage``
    ``:309`` / ``ImageUtils.safeRead``). Returns None on undecodable input."""
    try:
        import cv2
        arr = np.frombuffer(raw, dtype=np.uint8)
        img = cv2.imdecode(arr, cv2.IMREAD_UNCHANGED)
        if img is None:
            return None
        return make_image(img, origin)
    except ImportError:
        pass
    try:
        import io

        from PIL import Image
        img = Image.open(io.BytesIO(raw))
        rgb = np.asarray(img.convert("RGB"))
        return make_image(rgb[:, :, ::-1], origin)  # RGB → BGR
    except Exception:
        return None


def encode_image(image: dict, ext: str = ".png") -> bytes:
    """Image struct → compressed bytes (parity: ``encodeImage:408``)."""
    import cv2
    ok, buf = cv2.imencode(ext, image["data"])
    if not ok:
        raise ValueError(f"could not encode image as {ext}")
    return bytes(buf)


def _normalize(batch: np.ndarray, scale: float, mean, std) -> np.ndarray:
    x = batch.astype(np.float32) * np.float32(scale)
    if mean is not None:
        x = x - np.asarray(mean, np.float32)
    if std is not None:
        x = x / np.asarray(std, np.float32)
    return x


def to_nhwc_tensor(images, scale: float = 1.0, mean=None, std=None,
                   bgr_to_rgb: bool = False) -> np.ndarray:
    """Batch of same-shape image structs → (N,H,W,C) float32 — the
    TPU-preferred layout (convs hit the MXU without transposes)."""
    batch = np.stack([im["data"] for im in images])
    if bgr_to_rgb and batch.shape[-1] >= 3:
        batch = batch[..., [2, 1, 0] + list(range(3, batch.shape[-1]))]
    return _normalize(batch, scale, mean, std)


def to_nchw_tensor(images, scale: float = 1.0, mean=None, std=None,
                   bgr_to_rgb: bool = False) -> np.ndarray:
    """Same, transposed to (N,C,H,W) — the ONNX convention (parity with the
    reference's CHW tensor output, ``ImageTransformer.scala:417+``).
    mean/std are per-channel (C,), applied before the transpose."""
    x = to_nhwc_tensor(images, scale, mean, std, bgr_to_rgb)
    return np.ascontiguousarray(np.transpose(x, (0, 3, 1, 2)))
