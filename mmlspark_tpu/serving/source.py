"""Source/sink + request parsing DSL for serving.

Parity: ``HTTPSource``/``HTTPSink`` v1 (``streaming/HTTPSource.scala:44,179``)
and the ``IOImplicits`` DSL (``io/IOImplicits.scala:20-220``):
``spark.readStream.server`` → :class:`HTTPSource`, ``df.parseRequest`` →
:func:`parse_request`, ``df.makeReply`` → :func:`make_reply`,
``writeStream.server.replyTo`` → :class:`HTTPSink`.
"""

from __future__ import annotations

import json
from typing import Dict, Optional

import numpy as np

from ..core.dataframe import DataFrame, object_col
from ..core.params import Param
from ..core.serialize import to_jsonable
from ..core.pipeline import Transformer
from .server import WorkerServer

__all__ = ["HTTPSource", "HTTPSink", "parse_request", "make_reply"]

ID_COL = "id"
REQUEST_COL = "request"
REPLY_COL = "reply"


class HTTPSource:
    """Pull parked requests as DataFrame micro-batches.

    Each batch carries ``id`` (request id, the reply routing key — parity
    with the (machineIp, requestId, partition) triple of
    ``HTTPSourceV2.scala:657-660``) and ``request`` (:class:`HTTPRequestData`).
    """

    def __init__(self, server: WorkerServer):
        self.server = server

    def read_batch(self, max_rows: int = 1024, timeout: float = 0.1) -> DataFrame:
        cached = self.server.get_batch(max_rows, timeout)
        return DataFrame({ID_COL: object_col(c.request_id for c in cached),
                          REQUEST_COL: object_col(c.request for c in cached)})


class HTTPSink:
    """Route a reply column back to the parked connections
    (parity: ``HTTPSink``/``HTTPDataWriter.write`` ``HTTPSinkV2.scala:105-148``)."""

    def __init__(self, server: WorkerServer, reply_col: str = REPLY_COL,
                 id_col: str = ID_COL):
        self.server = server
        self.reply_col = reply_col
        self.id_col = id_col

    def write_batch(self, df: DataFrame) -> int:
        n = 0
        for rid, val in zip(df[self.id_col], df[self.reply_col]):
            ok = self.server.reply_json(rid, to_jsonable(val))
            n += int(ok)
        return n


def parse_request(df: DataFrame, schema: Optional[Dict[str, type]] = None,
                  request_col: str = REQUEST_COL) -> DataFrame:
    """JSON request bodies → typed columns (parity: ``df.parseRequest``,
    ``IOImplicits.scala:134-170``). ``schema`` maps field → dtype; without a
    schema the parsed dict lands in a ``body`` column."""
    reqs = df[request_col]
    bodies = []
    for r in reqs:
        try:
            bodies.append(json.loads(r.entity.string_content()) if r.entity else {})
        except (json.JSONDecodeError, AttributeError):
            bodies.append({})
    out = df.drop(request_col)
    if schema is None:
        return out.with_column("body", object_col(bodies))
    for name, dtype in schema.items():
        vals = [b.get(name) for b in bodies]
        if dtype in (float, int):
            arr = np.asarray([dtype(v) if v is not None else np.nan for v in vals])
        elif dtype is list:
            arr = object_col(np.asarray(v) if v is not None else None
                             for v in vals)
        else:
            arr = object_col(vals)
        out = out.with_column(name, arr)
    return out


def make_reply(df: DataFrame, value_col: str, reply_col: str = REPLY_COL) -> DataFrame:
    """Wrap a value column as the reply column (parity: ``df.makeReply``,
    ``IOImplicits.scala:172-186``)."""
    return df.with_column(reply_col, df[value_col])


class ParseRequest(Transformer):
    """Stage form of :func:`parse_request`, so serving pipelines can be a
    single ``PipelineModel``."""

    request_col = Param(str, default=REQUEST_COL, doc="request column name")
    schema = Param(dict, default=None, doc="field → type map (None: raw body)")

    def _transform(self, df: DataFrame) -> DataFrame:
        return parse_request(df, self.get_or_none("schema"), self.get("request_col"))


class MakeReply(Transformer):
    value_col = Param(str, doc="column to send back")
    reply_col = Param(str, default=REPLY_COL, doc="reply column name")

    def _transform(self, df: DataFrame) -> DataFrame:
        return make_reply(df, self.get("value_col"), self.get("reply_col"))
