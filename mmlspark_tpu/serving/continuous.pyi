# Hand-written stub (continuous.py defines no PipelineStage, so codegen
# skips it); kept in sync by tpulint rule TPU006 (stub-drift).
import threading
from typing import Any, Dict, List, Optional

class _Request:
    rid: int
    prompt: Any
    max_new: int
    temperature: float
    top_k: int
    top_p: float
    seed: int
    prefix_key: Optional[str]
    prefix_len: Optional[int]
    error: Optional[Exception]
    tokens: List[int]
    done: bool
    event: threading.Event
    submitted_at: float
    first_token_at: Optional[float]
    finished_at: Optional[float]
    cost_cls: Any
    cost_trace: Optional[str]
    session_id: str
    pre_emitted: List[int]
    journaled: int

class ContinuousDecoder:
    stats: Dict[str, int]
    def __init__(self, params: Dict, cfg: Any, *,
                 max_slots: int = ..., max_len: int = ...,
                 eos_id: Optional[int] = ...,
                 mesh: Optional[Any] = ...,
                 prefix_cache_size: int = ...,
                 steps_per_dispatch: int = ...,
                 pipeline_depth: int = ...,
                 prefill_ahead: int = ...,
                 draft_params: Optional[Dict] = ...,
                 draft_cfg: Optional[Any] = ...,
                 gamma: int = ...,
                 page_size: int = ...,
                 prefill_chunk: int = ...,
                 kv_pages: Optional[int] = ...,
                 autotune: bool = ...,
                 defrag_threshold: Optional[int] = ...,
                 paged_attn: Optional[str] = ...,
                 kv_dtype: Optional[str] = ...,
                 quant_probe: int = ...,
                 slo_model: str = ...,
                 journal: Optional[Any] = ...) -> None: ...
    def submit(self, prompt_ids: Any, max_new_tokens: int = ..., *,
               temperature: float = ..., top_k: int = ...,
               top_p: float = ..., seed: int = ...,
               prefix_key: Optional[str] = ...,
               prefix_len: Optional[int] = ...,
               session_id: Optional[str] = ...,
               _journal_record: bool = ...) -> _Request: ...
    def result(self, req: _Request,
               timeout: Optional[float] = ...) -> List[int]: ...
    def session_result(self, req: _Request,
                       timeout: Optional[float] = ...) -> List[int]: ...
    def checkpoint_session(self, req: _Request, *,
                           export_kv: bool = ...) -> dict: ...
    def restore_session(self, sess: dict,
                        kv_blob: Optional[dict] = ...) -> _Request: ...
    def step(self) -> int: ...
    def flush(self) -> None: ...
    def cancel_all(self) -> None: ...
    def serve_forever(self, idle_sleep: float = ...,
                      max_failures: int = ...,
                      failure_backoff: float = ...) -> None: ...
    def start(self) -> threading.Thread: ...
    def stop(self) -> None: ...
