"""Versioned model registry: hot load/unload, canary rollout, shadow traffic.

The control plane of the multi-model traffic plane. Models are named,
versioned entries (``name@version``) with a per-version state machine::

    loading -> canary -> live -> draining -> retired

``load()`` stages the version's handle and runs its (ladder-aware)
``warm_up`` OFF the request path before the version becomes routable —
the first real request never eats an XLA compile stall. ``unload()`` /
``retire()`` drain in-flight work first, then release what the handle
holds: ``_device_params`` staged on device (models/jax_model.py) and any
``PagedKVPool`` (whose ``close()`` returns its ``ResidencyManager``
reservation).

Rollout: a candidate in ``canary`` receives a configured percentage of
the model's traffic (deterministic per-request split, so retries of one
request stay on one version). :meth:`check_canaries` compares the
candidate's rolling p99 / error rate against the incumbent's — both read
from the ``SloTracker``'s per-``{transport,route,model,tenant}`` windows,
where the model dimension carries ``name@version`` — and auto-rolls the
candidate back when it breaches the incumbent by the configured margins.
Shadow traffic mirrors a sampled fraction of incumbent requests to the
candidate; the shadow's reply is never sent to the caller, only joined
against the primary's and diffed (the trace ids of both land in the
event log, so the FlightRecorder holds the full pair).

Tenant config (the weights ``AdmissionQueue`` reads) also lives here —
one registry is THE control surface the ``/models`` admin route edits.

Process-global accessors follow the repo's singleton idiom:
``get_registry()`` / ``set_registry()`` / ``reset_registry()``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ..observability import counter as _metric_counter
from ..observability import gauge as _metric_gauge
from ..observability import get_tracker as _get_tracker
from ..observability import log_event as _log_event
from ..observability import tracing as _tracing

__all__ = ["ModelRegistry", "ModelVersion", "Resolution", "VERSION_STATES",
           "WORKER_LIVENESS_STATES",
           "get_registry", "set_registry", "reset_registry"]

#: the per-version lifecycle, in order; transitions only move forward
#: except rollback (canary -> retired via draining)
VERSION_STATES = ("loading", "canary", "live", "draining", "retired")

#: the per-worker liveness lifecycle the driver's sweeper walks
#: (serving/distributed.py): heartbeats keep a worker ``alive``; a missed
#: beat past the liveness interval makes it ``suspect``; past
#: interval x sweep-multiplier the sweeper issues a ``dead`` verdict and
#: reassigns its journaled sessions. ``draining`` is the operator-initiated
#: graceful path (excluded from routing, sessions handed off warm).
WORKER_LIVENESS_STATES = ("alive", "suspect", "draining", "dead")

_M_VERSIONS = _metric_gauge(
    "mmlspark_registry_versions",
    "Registered model versions by lifecycle state", ("state",))
_M_LOADS = _metric_counter(
    "mmlspark_registry_loads_total",
    "Model version load attempts by outcome", ("outcome",))
_M_ROLLBACKS = _metric_counter(
    "mmlspark_registry_rollbacks_total",
    "Canary auto/manual rollbacks", ("reason",))
_M_CANARY = _metric_counter(
    "mmlspark_registry_canary_routed_total",
    "Model resolutions by rollout decision", ("decision",))
_M_SHADOW = _metric_counter(
    "mmlspark_registry_shadow_requests_total",
    "Requests mirrored to a shadow (candidate) version")
_M_SHADOW_DIFFS = _metric_counter(
    "mmlspark_registry_shadow_diffs_total",
    "Joined primary/shadow reply pairs by verdict", ("verdict",))


class ModelVersion:
    """One registered ``name@version``: its handle (the callable /
    transform / model object serving engines dispatch to), lifecycle
    state, rollout knobs, and in-flight accounting."""

    def __init__(self, name: str, version: str, handle=None,
                 canary_percent: float = 0.0, shadow_percent: float = 0.0,
                 unload_fn: Optional[Callable[[], None]] = None):
        self.name = str(name)
        self.version = str(version)
        self.handle = handle
        self.state = "loading"
        self.canary_percent = float(canary_percent)
        self.shadow_percent = float(shadow_percent)
        self.unload_fn = unload_fn
        self.error: Optional[str] = None
        self.created_at = time.time()
        self.warmed_seconds: Optional[float] = None
        self.in_flight = 0
        self.resolved_total = 0

    @property
    def label(self) -> str:
        return f"{self.name}@{self.version}"

    def snapshot(self) -> Dict[str, object]:
        return {"name": self.name, "version": self.version,
                "label": self.label, "state": self.state,
                "canary_percent": self.canary_percent,
                "shadow_percent": self.shadow_percent,
                "error": self.error,
                "warmed_seconds": self.warmed_seconds,
                "in_flight": self.in_flight,
                "resolved_total": self.resolved_total}


class Resolution:
    """Outcome of one model resolution: the version label to serve from,
    and optionally a shadow label to mirror (never answer from)."""

    __slots__ = ("label", "shadow", "decision")

    def __init__(self, label: str, shadow: Optional[str] = None,
                 decision: str = "passthrough"):
        self.label = label
        self.shadow = shadow
        self.decision = decision


def _bucket(request_id: Optional[str], salt: str) -> int:
    """Deterministic 0-99 split bucket for a request id — stable across
    retries of the same request, independent per salt."""
    import hashlib
    rid = request_id or _tracing.new_request_id()
    digest = hashlib.sha1(f"{salt}:{rid}".encode("utf-8")).digest()
    return digest[0] % 100


class ModelRegistry:
    """The versioned model catalog + rollout controller + tenant config.

    Canary auto-rollback margins: the candidate rolls back when, with at
    least ``min_requests`` observed in its rolling window, its window
    error rate exceeds the incumbent's by ``err_margin`` (absolute) OR
    its window p99 exceeds ``p99_margin`` times the incumbent's OR its
    window KV quantization error (``kv_quant_error`` — the relative RMS
    reported by a quantized engine's dequant oracle probe) exceeds the
    incumbent's by ``quant_margin`` (absolute). The quant breach is what
    lets a ``model@quant`` canary A/B against a ``model@bf16`` incumbent
    with automatic rollback when the quantized KV plane drifts.
    ``check_every`` bounds hot-path cost: the rollback check runs every
    N canary resolutions (and on every :meth:`check_canaries`, which
    heartbeats call off the request path).
    """

    def __init__(self, err_margin: float = 0.05, p99_margin: float = 1.5,
                 min_requests: int = 20, check_every: int = 16,
                 shadow_keep: int = 64, quant_margin: float = 0.05):
        self.err_margin = float(err_margin)
        self.p99_margin = float(p99_margin)
        self.quant_margin = float(quant_margin)
        self.min_requests = int(min_requests)
        self.check_every = max(1, int(check_every))
        self._lock = threading.Lock()
        #: name → {version: ModelVersion}
        self._models: Dict[str, Dict[str, ModelVersion]] = {}
        #: tenant → weight (AdmissionQueue reads via tenant_weight)
        self._tenants: Dict[str, float] = {}
        self._canary_resolves = 0
        #: rollback history (most recent last, bounded)
        self._rollbacks: deque = deque(maxlen=32)
        #: primary request id → pending shadow join record
        self._shadow_pending: Dict[str, Dict[str, object]] = {}
        #: completed shadow diffs (most recent last, bounded)
        self._shadow_diffs: deque = deque(maxlen=int(shadow_keep))

    # -- lifecycle -----------------------------------------------------------
    def _set_state(self, mv: ModelVersion, state: str) -> None:
        """Transition (caller holds no lock requirement) + gauge refresh +
        event — every state change leaves an audit trail."""
        mv.state = state
        self._refresh_state_gauge()
        _log_event("registry_state", model=mv.name, version=mv.version,
                   state=state)

    def _refresh_state_gauge(self) -> None:
        counts = {s: 0 for s in VERSION_STATES}
        with self._lock:
            for versions in self._models.values():
                for mv in versions.values():
                    counts[mv.state] = counts.get(mv.state, 0) + 1
        for state, n in counts.items():
            _M_VERSIONS.set(n, state=state)

    def load(self, name: str, version: str, handle=None,
             warm_up: Optional[Callable[[], object]] = None,
             canary_percent: float = 0.0, shadow_percent: float = 0.0,
             unload_fn: Optional[Callable[[], None]] = None,
             block: bool = True) -> ModelVersion:
        """Register ``name@version`` and make it routable.

        The version is held in ``loading`` while ``warm_up`` runs (NOT
        routable — resolve() skips it), then becomes ``live`` when the
        model has no live incumbent, else ``canary`` at
        ``canary_percent``. ``block=False`` runs warm-up on a background
        thread and returns immediately (state still ``loading``)."""
        mv = ModelVersion(name, version, handle=handle,
                          canary_percent=canary_percent,
                          shadow_percent=shadow_percent,
                          unload_fn=unload_fn)
        with self._lock:
            versions = self._models.setdefault(mv.name, {})
            if mv.version in versions \
                    and versions[mv.version].state != "retired":
                raise ValueError(f"{mv.label} is already registered "
                                 f"({versions[mv.version].state})")
            versions[mv.version] = mv
        self._set_state(mv, "loading")
        if block:
            self._warm_and_activate(mv, warm_up)
        else:
            t = threading.Thread(
                target=_tracing.propagate(self._warm_and_activate),
                args=(mv, warm_up), daemon=True,
                name=f"registry-warmup-{mv.label}")
            t.start()
        return mv

    def _warm_and_activate(self, mv: ModelVersion,
                           warm_up: Optional[Callable[[], object]]) -> None:
        t0 = time.perf_counter()
        if warm_up is not None:
            try:
                warm_up()
            except Exception as exc:
                mv.error = repr(exc)
                self._set_state(mv, "retired")
                _M_LOADS.inc(outcome="error")
                _log_event("registry_warmup_failed", model=mv.name,
                           version=mv.version, error=repr(exc))
                return
        mv.warmed_seconds = round(time.perf_counter() - t0, 6)
        with self._lock:
            has_live = any(v.state == "live"
                           for v in self._models[mv.name].values()
                           if v is not mv)
        self._set_state(mv, "canary" if has_live else "live")
        _M_LOADS.inc(outcome="ok")

    def promote(self, name: str, version: str,
                drain_timeout: float = 5.0) -> ModelVersion:
        """Canary → live; the previous incumbent drains and retires."""
        with self._lock:
            mv = self._get_locked(name, version)
            if mv.state not in ("canary", "loading"):
                raise ValueError(f"{mv.label} is {mv.state}, not canary")
            incumbents = [v for v in self._models[mv.name].values()
                          if v.state == "live"]
        self._set_state(mv, "live")
        for old in incumbents:
            self.retire(old.name, old.version, drain_timeout=drain_timeout)
        return mv

    def rollback(self, name: str, version: Optional[str] = None,
                 reason: str = "manual") -> Optional[ModelVersion]:
        """Pull a canary out of rotation (auto-rollback's shared path).
        ``version=None`` rolls back whatever canary the model has."""
        with self._lock:
            versions = self._models.get(str(name), {})
            if version is None:
                cands = [v for v in versions.values()
                         if v.state == "canary"]
                mv = cands[0] if cands else None
            else:
                mv = versions.get(str(version))
            if mv is None or mv.state not in ("canary", "loading"):
                return None
            self._rollbacks.append(
                {"t": time.time(), "model": mv.name,
                 "version": mv.version, "reason": reason})
        _M_ROLLBACKS.inc(reason="auto" if reason != "manual" else "manual")
        _log_event("registry_rollback", model=mv.name, version=mv.version,
                   reason=reason)
        self.retire(mv.name, mv.version)
        return mv

    def retire(self, name: str, version: str,
               drain_timeout: float = 5.0) -> Dict[str, object]:
        """Drain in-flight work, then release device state: clears the
        handle's staged ``_device_params`` and closes its ``pool``
        (returning the ``ResidencyManager`` reservation), then runs the
        version's ``unload_fn``. Safe to call from any state."""
        with self._lock:
            mv = self._get_locked(name, version)
        if mv.state == "retired":
            return {"label": mv.label, "drained": True}
        self._set_state(mv, "draining")
        deadline = time.monotonic() + max(0.0, float(drain_timeout))
        while mv.in_flight > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        drained = mv.in_flight == 0
        handle = mv.handle
        # release staged device params (models/jax_model.py keeps them in
        # _device_params keyed by device ladder slot)
        if handle is not None and hasattr(handle, "_device_params"):
            handle._device_params = {}
        pool = getattr(handle, "pool", None)
        if pool is not None and hasattr(pool, "close"):
            try:
                pool.close()
            except Exception as exc:
                _log_event("registry_pool_close_failed", model=mv.name,
                           version=mv.version, error=repr(exc))
        if mv.unload_fn is not None:
            try:
                mv.unload_fn()
            except Exception as exc:
                _log_event("registry_unload_failed", model=mv.name,
                           version=mv.version, error=repr(exc))
        mv.handle = None
        self._set_state(mv, "retired")
        _log_event("registry_retired", model=mv.name, version=mv.version,
                   drained=drained)
        return {"label": mv.label, "drained": drained}

    def unload(self, name: str, version: str,
               drain_timeout: float = 5.0) -> Dict[str, object]:
        """Alias for :meth:`retire` — the admin-facing verb."""
        return self.retire(name, version, drain_timeout=drain_timeout)

    def _get_locked(self, name: str, version: str) -> ModelVersion:
        versions = self._models.get(str(name), {})
        mv = versions.get(str(version))
        if mv is None:
            raise KeyError(f"unknown model version {name}@{version}")
        return mv

    # -- resolution ----------------------------------------------------------
    def resolve(self, name: str,
                request_id: Optional[str] = None) -> Resolution:
        """Pick the version that serves this request. Unregistered names
        pass through unchanged (the single-model deployments that never
        touch the registry keep their ``model="default"`` SLO class).
        Canary split is deterministic per request id; shadow sampling is
        an independent split so shadow rate is not conditioned on the
        canary outcome."""
        with self._lock:
            versions = self._models.get(str(name))
            if not versions:
                return Resolution(str(name))
            live = [v for v in versions.values() if v.state == "live"]
            canary = [v for v in versions.values() if v.state == "canary"]
            incumbent = live[-1] if live else None
            candidate = canary[-1] if canary else None
            chosen = incumbent
            decision = "incumbent"
            if candidate is not None and incumbent is not None \
                    and candidate.canary_percent > 0 \
                    and _bucket(request_id, "canary") \
                    < candidate.canary_percent:
                chosen = candidate
                decision = "canary"
            elif incumbent is None and candidate is not None:
                # nothing live yet (first rollout): the canary serves
                chosen = candidate
                decision = "canary"
            if chosen is None:
                return Resolution(str(name))
            shadow = None
            if decision != "canary" and candidate is not None \
                    and candidate.shadow_percent > 0 \
                    and _bucket(request_id, "shadow") \
                    < candidate.shadow_percent:
                shadow = candidate.label
                candidate.in_flight += 1
            chosen.in_flight += 1
            chosen.resolved_total += 1
            if decision == "canary":
                self._canary_resolves += 1
                due = self._canary_resolves % self.check_every == 0
            else:
                due = False
        _M_CANARY.inc(decision=decision)
        if due:
            self.check_canaries()
        return Resolution(chosen.label, shadow=shadow, decision=decision)

    def note_done(self, label: str) -> None:
        """Reply landed for a request resolved to ``label`` — drop its
        in-flight count (the retire() drain barrier watches this)."""
        name, _, version = str(label).partition("@")
        with self._lock:
            mv = self._models.get(name, {}).get(version)
            if mv is not None and mv.in_flight > 0:
                mv.in_flight -= 1

    def handle_for(self, label: str):
        """The staged handle for ``name@version`` (None when unknown or
        unloaded) — serving engines dispatch per-version through this."""
        name, _, version = str(label).partition("@")
        with self._lock:
            mv = self._models.get(name, {}).get(version)
            return mv.handle if mv is not None else None

    # -- canary governance ---------------------------------------------------
    def _window_stats(self, label: str) -> Dict[str, object]:
        tracker = _get_tracker()
        win = tracker.model_window(label)
        return win

    def check_canaries(self) -> List[Dict[str, object]]:
        """Compare every canary's rolling window against its incumbent's
        and auto-roll back breaches. Returns one verdict per canary —
        heartbeats call this off the request path."""
        with self._lock:
            pairs = []
            for name, versions in self._models.items():
                live = [v for v in versions.values() if v.state == "live"]
                for mv in versions.values():
                    if mv.state == "canary" and live:
                        pairs.append((name, mv.label, live[-1].label))
        verdicts = []
        for name, cand_label, inc_label in pairs:
            cand = self._window_stats(cand_label)
            inc = self._window_stats(inc_label)
            verdict = {"model": name, "candidate": cand_label,
                       "incumbent": inc_label, "candidate_window": cand,
                       "incumbent_window": inc, "breach": None}
            if cand["count"] >= self.min_requests:
                if cand["error_rate"] > inc["error_rate"] + self.err_margin:
                    verdict["breach"] = (
                        f"error_rate {cand['error_rate']:.3f} > "
                        f"{inc['error_rate']:.3f} + {self.err_margin}")
                elif (cand.get("p99") is not None
                      and inc.get("p99") is not None
                      and cand["p99"] > inc["p99"] * self.p99_margin):
                    verdict["breach"] = (
                        f"p99 {cand['p99']:.4f}s > "
                        f"{inc['p99']:.4f}s x {self.p99_margin}")
                elif (cand.get("kv_quant_error") is not None
                      and cand["kv_quant_error"]
                      > (inc.get("kv_quant_error") or 0.0)
                      + self.quant_margin):
                    verdict["breach"] = (
                        f"kv_quant_error {cand['kv_quant_error']:.4f} > "
                        f"{inc.get('kv_quant_error') or 0.0:.4f} + "
                        f"{self.quant_margin}")
            if verdict["breach"]:
                _, _, v = cand_label.partition("@")
                self.rollback(name, v, reason=verdict["breach"])
            verdicts.append(verdict)
        return verdicts

    # -- shadow traffic ------------------------------------------------------
    def shadow_begin(self, primary_id: str, shadow_id: str,
                     label: str, trace_id: Optional[str] = None) -> None:
        """Record that ``primary_id`` is being mirrored to ``shadow_id``
        on version ``label`` — the join the replies complete."""
        with self._lock:
            # bound the pending table: an orphaned join (lost reply)
            # must not leak forever
            if len(self._shadow_pending) >= 256:
                self._shadow_pending.pop(next(iter(self._shadow_pending)))
            self._shadow_pending[str(primary_id)] = {
                "shadow_id": str(shadow_id), "label": str(label),
                "trace_id": trace_id, "primary": None, "shadow": None}
        _M_SHADOW.inc()

    def shadow_result(self, primary_id: str, body: Optional[bytes],
                      from_shadow: bool) -> None:
        """One side of a mirrored pair answered; when both sides are in,
        diff and record the verdict (the reply content itself stays in
        the FlightRecorder via the recorded trace ids)."""
        with self._lock:
            rec = self._shadow_pending.get(str(primary_id))
            if rec is None:
                return
            rec["shadow" if from_shadow else "primary"] = body or b""
            if rec["primary"] is None or rec["shadow"] is None:
                return
            self._shadow_pending.pop(str(primary_id))
            verdict = ("match" if rec["primary"] == rec["shadow"]
                       else "diff")
            entry = {"t": time.time(), "primary_id": str(primary_id),
                     "shadow_id": rec["shadow_id"], "label": rec["label"],
                     "trace_id": rec["trace_id"], "verdict": verdict}
            self._shadow_diffs.append(entry)
        _M_SHADOW_DIFFS.inc(verdict=verdict)
        _log_event("shadow_diff", **entry)

    def shadow_diffs(self) -> List[Dict[str, object]]:
        with self._lock:
            return list(self._shadow_diffs)

    # -- tenant config -------------------------------------------------------
    def set_tenant(self, tenant: str, weight: float) -> None:
        w = float(weight)
        if w <= 0:
            raise ValueError("tenant weight must be positive")
        with self._lock:
            self._tenants[str(tenant)] = w
        _log_event("registry_tenant", tenant=str(tenant), weight=w)

    def tenant_weight(self, tenant: str) -> float:
        with self._lock:
            return self._tenants.get(str(tenant), 1.0)

    def tenants(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._tenants)

    # -- introspection -------------------------------------------------------
    def versions(self, name: str) -> List[ModelVersion]:
        with self._lock:
            return list(self._models.get(str(name), {}).values())

    def snapshot(self) -> Dict[str, object]:
        """Full JSON-safe registry state — the /debug/registry payload."""
        with self._lock:
            models = {name: [mv.snapshot() for mv in versions.values()]
                      for name, versions in self._models.items()}
            rollbacks = list(self._rollbacks)
            tenants = dict(self._tenants)
            pending = len(self._shadow_pending)
        return {"models": models, "tenants": tenants,
                "rollbacks": rollbacks,
                "shadow_pending": pending,
                "shadow_diffs": self.shadow_diffs(),
                "margins": {"err_margin": self.err_margin,
                            "p99_margin": self.p99_margin,
                            "quant_margin": self.quant_margin,
                            "min_requests": self.min_requests}}

    def digest(self) -> Dict[str, object]:
        """Compact registry state for heartbeat piggybacking: per model,
        which version is live/canary and the lifecycle state counts."""
        with self._lock:
            models = {}
            for name, versions in self._models.items():
                live = [v.version for v in versions.values()
                        if v.state == "live"]
                canary = [v.version for v in versions.values()
                          if v.state == "canary"]
                models[name] = {
                    "live": live[-1] if live else None,
                    "canary": canary[-1] if canary else None,
                    "versions": len(versions)}
            return {"models": models, "tenants": dict(self._tenants),
                    "rollbacks": len(self._rollbacks)}

    def reset(self) -> None:
        with self._lock:
            self._models.clear()
            self._tenants.clear()
            self._rollbacks.clear()
            self._shadow_pending.clear()
            self._shadow_diffs.clear()
            self._canary_resolves = 0
        self._refresh_state_gauge()


_registry: Optional[ModelRegistry] = None
_registry_lock = threading.Lock()


def get_registry() -> ModelRegistry:
    """Process-global registry (the one the serving plane consults)."""
    global _registry
    with _registry_lock:
        if _registry is None:
            _registry = ModelRegistry()
        return _registry


def set_registry(registry: Optional[ModelRegistry]) -> None:
    global _registry
    with _registry_lock:
        _registry = registry


def reset_registry() -> None:
    set_registry(None)
