"""HTTP generation endpoint over the continuous-batching decoder.

Completes the LLM-serving story (``serving/continuous.py``): clients POST
``{"tokens": [...], "max_new": N}`` and get ``{"tokens": [...]}`` back,
with every in-flight request sharing the slot-pool decoder. The HTTP
plumbing is the same WorkerServer the stateless engine uses
(parity anchor: ``HTTPSourceV2.scala:476-697``); what's new is the
lifecycle — a request parks across MANY engine ticks instead of one
transform, so the loop interleaves (admit → tick → reply-finished) rather
than (drain → transform → reply).

One driver thread owns the decoder (submissions ride the decoder's own
lock); replies route back through the server's request cache exactly like
batch replies, so journaling/replay semantics are untouched.
"""

from __future__ import annotations

import json
import logging
import threading
import traceback
from typing import Dict, Optional, Tuple

import numpy as np

from .continuous import ContinuousDecoder
from .server import WorkerServer

__all__ = ["GenerationEngine"]

_log = logging.getLogger("mmlspark_tpu.serving")


class GenerationEngine:
    """Serve ``{"tokens": [...], "max_new": N}`` → ``{"tokens": [...]}``
    over a :class:`ContinuousDecoder` slot pool."""

    def __init__(self, params, cfg, *, max_slots: int = 4,
                 max_len: int = 256, eos_id: Optional[int] = None,
                 default_max_new: int = 32,
                 host: str = "127.0.0.1", port: int = 0,
                 api_path: str = "/generate",
                 reply_timeout: float = 120.0,
                 transport: str = "threaded",
                 steps_per_dispatch: int = 1):
        self.decoder = ContinuousDecoder(
            params, cfg, max_slots=max_slots, max_len=max_len,
            eos_id=eos_id, steps_per_dispatch=steps_per_dispatch)
        self.default_max_new = int(default_max_new)
        self.server = WorkerServer(host, port, api_path,
                                   reply_timeout=reply_timeout,
                                   transport=transport)
        #: decoder rid -> (server request id, decoder ticket) — ONE source
        #: of truth for in-flight work, mutated at one site per transition
        self._inflight: Dict[int, Tuple[str, object]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return self.server.address.rstrip("/") + "/"

    def start(self) -> "GenerationEngine":
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"generation-engine-{self.server.port}")
        self._thread.start()
        return self

    def _admit_one(self, cached) -> None:
        """Parse + submit ONE request; any failure 400s only that request
        (a malformed field must not poison the batch or the in-flight set —
        the same isolation ServingEngine gets from its per-batch try)."""
        rid = cached.request_id
        try:
            ent = cached.request.entity
            body = json.loads(ent.string_content()) if ent else {}
            toks = body.get("tokens")
            if not toks:
                raise ValueError("missing or empty 'tokens'")
            mn = int(body.get("max_new", self.default_max_new))
            pl = body.get("prefix_len")
            ticket = self.decoder.submit(
                np.asarray(toks, np.int32), mn,
                temperature=float(body.get("temperature", 0.0)),
                top_k=int(body.get("top_k", 0)),
                top_p=float(body.get("top_p", 1.0)),
                seed=int(body.get("seed", 0)),
                prefix_key=body.get("prefix_key"),
                prefix_len=int(pl) if pl is not None else None)
        except Exception as e:
            self.server.reply_json(rid, {"error": str(e)}, status=400)
            return
        self._inflight[ticket.rid] = (rid, ticket)

    def _admit_http(self, idle: bool) -> None:
        # mid-stream (live slots) the drain is non-blocking: a blocking
        # poll here would add its timeout to EVERY emitted token's latency;
        # only an idle engine waits for work
        for cached in self.server.get_batch(64, timeout=0.002 if idle else 0):
            self._admit_one(cached)

    def _reply_finished(self) -> None:
        done = [drid for drid, (_, t) in self._inflight.items() if t.done]
        for drid in done:
            rid, ticket = self._inflight.pop(drid)
            if getattr(ticket, "error", None) is not None:
                # per-request admit failure (e.g. prefix mismatch): 400s
                # this client alone, the batch keeps decoding
                self.server.reply_json(rid, {"error": str(ticket.error)},
                                       status=400)
            else:
                self.server.reply_json(rid, {"tokens": ticket.tokens})
        if done:
            self.server.commit_epoch()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._admit_http(idle=not self._inflight)
                stepped = self.decoder.step()
                self._reply_finished()
                if stepped == 0 and not self._inflight:
                    self._stop.wait(0.005)
            except Exception:
                _log.error("generation engine tick failed:\n%s",
                           traceback.format_exc())
                # fail every in-flight request rather than hang clients,
                # and free the slot pool (nothing will retire those slots
                # if step() keeps raising)
                for rid, _ in self._inflight.values():
                    self.server.reply_json(
                        rid, {"error": "internal error"}, status=500)
                self._inflight.clear()
                try:
                    self.decoder.cancel_all()
                except Exception:
                    _log.error("decoder cancel_all failed:\n%s",
                               traceback.format_exc())
                # backoff: a persistent failure must not busy-spin the host
                self._stop.wait(0.2)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        # fail in-flight clients NOW instead of leaving their connections
        # parked until reply_timeout's 504
        for rid, _ in self._inflight.values():
            self.server.reply_json(
                rid, {"error": "server shutting down"}, status=503)
        self._inflight.clear()
        self.decoder.cancel_all()
        self.decoder.stop()
        self.server.close()

    def __enter__(self) -> "GenerationEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
