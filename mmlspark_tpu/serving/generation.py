"""HTTP generation endpoint over the continuous-batching decoder.

Completes the LLM-serving story (``serving/continuous.py``): clients POST
``{"tokens": [...], "max_new": N}`` and get ``{"tokens": [...]}`` back,
with every in-flight request sharing the slot-pool decoder. The HTTP
plumbing is the same WorkerServer the stateless engine uses
(parity anchor: ``HTTPSourceV2.scala:476-697``); what's new is the
lifecycle — a request parks across MANY engine ticks instead of one
transform, so the loop interleaves (admit → tick → reply-finished) rather
than (drain → transform → reply).

One driver thread owns the decoder (submissions ride the decoder's own
lock); replies route back through the server's request cache exactly like
batch replies, so journaling/replay semantics are untouched.
"""

from __future__ import annotations

import json
import logging
import threading
import traceback
from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

from .continuous import ContinuousDecoder
from .server import StreamingReply, WorkerServer

__all__ = ["GenerationEngine"]

_log = logging.getLogger("mmlspark_tpu.serving")


@dataclass
class _InFlight:
    """One parked generation: the server request, the decoder ticket, an
    open SSE stream when the client asked for one, and how many tokens
    that stream has already been sent."""
    rid: str
    ticket: object
    stream: Optional[StreamingReply] = None
    sent: int = 0


class GenerationEngine:
    """Serve ``{"tokens": [...], "max_new": N}`` → ``{"tokens": [...]}``
    over a :class:`ContinuousDecoder` slot pool."""

    def __init__(self, params, cfg, *, max_slots: int = 4,
                 max_len: int = 256, eos_id: Optional[int] = None,
                 default_max_new: int = 32,
                 host: str = "127.0.0.1", port: int = 0,
                 api_path: str = "/generate",
                 reply_timeout: float = 120.0,
                 transport: str = "threaded",
                 steps_per_dispatch: int = 1,
                 pipeline_depth: int = 2,
                 prefill_ahead: int = 0,
                 draft_params=None, draft_cfg=None, gamma: int = 4,
                 page_size: int = 16, prefill_chunk: int = 256,
                 kv_pages: Optional[int] = None, autotune: bool = False,
                 paged_attn: Optional[str] = None, mesh=None):
        self.decoder = ContinuousDecoder(
            params, cfg, max_slots=max_slots, max_len=max_len,
            eos_id=eos_id, steps_per_dispatch=steps_per_dispatch,
            pipeline_depth=pipeline_depth, prefill_ahead=prefill_ahead,
            draft_params=draft_params, draft_cfg=draft_cfg, gamma=gamma,
            page_size=page_size, prefill_chunk=prefill_chunk,
            kv_pages=kv_pages, autotune=autotune,
            paged_attn=paged_attn, mesh=mesh)
        self.default_max_new = int(default_max_new)
        self.server = WorkerServer(host, port, api_path,
                                   reply_timeout=reply_timeout,
                                   transport=transport)
        #: decoder rid -> _InFlight — ONE source of truth for in-flight
        #: work, mutated at one site per transition
        self._inflight: Dict[int, _InFlight] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return self.server.address.rstrip("/") + "/"

    def start(self) -> "GenerationEngine":
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"generation-engine-{self.server.port}")
        self._thread.start()
        return self

    def _admit_one(self, cached) -> None:
        """Parse + submit ONE request; any failure 400s only that request
        (a malformed field must not poison the batch or the in-flight set —
        the same isolation ServingEngine gets from its per-batch try).
        ``"stream": true`` opens a Server-Sent-Events reply instead: each
        engine tick pushes the newly emitted tokens as a ``data:`` event,
        and the final event carries ``done`` plus the full sequence."""
        rid = cached.request_id
        try:
            ent = cached.request.entity
            body = json.loads(ent.string_content()) if ent else {}
            toks = body.get("tokens")
            if not toks:
                raise ValueError("missing or empty 'tokens'")
            mn = int(body.get("max_new", self.default_max_new))
            pl = body.get("prefix_len")
            stream = bool(body.get("stream", False))
            ticket = self.decoder.submit(
                np.asarray(toks, np.int32), mn,
                temperature=float(body.get("temperature", 0.0)),
                top_k=int(body.get("top_k", 0)),
                top_p=float(body.get("top_p", 1.0)),
                seed=int(body.get("seed", 0)),
                prefix_key=body.get("prefix_key"),
                prefix_len=int(pl) if pl is not None else None)
        except Exception as e:
            self.server.reply_json(rid, {"error": str(e)}, status=400)
            return
        handle = self.server.reply_stream(rid) if stream else None
        self._inflight[ticket.rid] = _InFlight(rid, ticket, handle)

    def _admit_http(self, idle: bool) -> None:
        # mid-stream (live slots) the drain is non-blocking: a blocking
        # poll here would add its timeout to EVERY emitted token's latency;
        # only an idle engine waits for work
        for cached in self.server.get_batch(64, timeout=0.002 if idle else 0):
            self._admit_one(cached)

    def _pump_streams(self) -> None:
        """Push newly emitted tokens on every streaming reply."""
        for f in self._inflight.values():
            if f.stream is None:
                continue
            fresh = f.ticket.tokens[f.sent:]
            if fresh:
                f.stream.send_event({"tokens": list(fresh)})
                f.sent += len(fresh)

    def _reply_finished(self) -> None:
        done = [drid for drid, f in self._inflight.items()
                if f.ticket.done]
        for drid in done:
            f = self._inflight.pop(drid)
            rid, ticket, handle = f.rid, f.ticket, f.stream
            err = getattr(ticket, "error", None)
            if handle is not None:
                if err is not None:
                    handle.send_event({"error": str(err)})
                else:
                    handle.send_event({"done": True,
                                       "tokens": list(ticket.tokens)})
                handle.close()
            elif err is not None:
                # per-request admit failure (e.g. prefix mismatch): 400s
                # this client alone, the batch keeps decoding
                self.server.reply_json(rid, {"error": str(err)},
                                       status=400)
            else:
                self.server.reply_json(rid, {"tokens": ticket.tokens})
        if done:
            self.server.commit_epoch()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._admit_http(idle=not self._inflight)
                stepped = self.decoder.step()
                self._pump_streams()
                self._reply_finished()
                if stepped == 0 and not self._inflight:
                    self._stop.wait(0.005)
            except Exception:
                _log.error("generation engine tick failed:\n%s",
                           traceback.format_exc())
                # fail every in-flight request rather than hang clients,
                # and free the slot pool (nothing will retire those slots
                # if step() keeps raising)
                self._fail_inflight("internal error", 500)
                try:
                    self.decoder.cancel_all()
                except Exception:
                    _log.error("decoder cancel_all failed:\n%s",
                               traceback.format_exc())
                # backoff: a persistent failure must not busy-spin the host
                self._stop.wait(0.2)

    def _fail_inflight(self, message: str, status: int) -> None:
        """Answer every in-flight request with an error — streaming
        clients get a final error event and a closed stream."""
        for f in self._inflight.values():
            if f.stream is not None:
                f.stream.send_event({"error": message})
                f.stream.close()
            else:
                self.server.reply_json(f.rid, {"error": message},
                                       status=status)
        self._inflight.clear()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        # fail in-flight clients NOW instead of leaving their connections
        # parked until reply_timeout's 504
        self._fail_inflight("server shutting down", 503)
        self.decoder.cancel_all()
        self.decoder.stop()
        self.server.close()

    def __enter__(self) -> "GenerationEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
