"""DataFrame-as-microservice: serve a pipeline over HTTP.

Parity surface: the reference's Spark Serving
(``org/apache/spark/sql/execution/streaming/{HTTPSource,DistributedHTTPSource}.scala``
and ``continuous/{HTTPSourceV2,HTTPSinkV2}.scala``):

* per-worker HTTP server with epoch-keyed request queues
  (``HTTPSourceV2.scala:476-697``, queues ``:512-518``)
* reply routing back to the originating connection
  (``HTTPSinkV2.scala:105-148``, ``WorkerServer.replyTo:536-554``)
* failure replay: unanswered requests of an epoch are re-served after a
  worker restart (``registerPartition`` rehydration, ``:489-506,556-568``)
* the ``IOImplicits`` DSL (``io/IOImplicits.scala:20-220``):
  ``parse_request`` / ``make_reply`` here are module functions instead of
  DataFrame extension methods.

TPU-first framing: requests buffer on the host and drain as *columnar
batches* into the same minibatch→pad→device path every other stage uses, so
a served model hits the chip with large static-shape batches instead of
row-at-a-time inference.
"""

from .admission import AdmissionQueue, ConsistentHashRing, TenantOverBudget
from .registry import (ModelRegistry, ModelVersion, Resolution,
                       get_registry, reset_registry, set_registry)
from .server import CachedRequest, WorkerServer
from .source import HTTPSource, parse_request, make_reply, HTTPSink
from .engine import ServingEngine
from .continuous import ContinuousDecoder
from .generation import GenerationEngine
from .kv_pool import (AFFINITY_HEADER, KVAutotuner, PagedKVPool,
                      PoolExhausted, affinity_headers)

__all__ = ["CachedRequest", "WorkerServer", "HTTPSource", "HTTPSink",
           "parse_request", "make_reply", "ServingEngine",
           "ContinuousDecoder", "GenerationEngine",
           "PagedKVPool", "KVAutotuner", "PoolExhausted",
           "AFFINITY_HEADER", "affinity_headers",
           "AdmissionQueue", "ConsistentHashRing", "TenantOverBudget",
           "ModelRegistry", "ModelVersion", "Resolution",
           "get_registry", "set_registry", "reset_registry"]
