"""The serving loop: drain requests → transform → route replies.

Parity: the continuous-mode request lifecycle of the reference
(SURVEY.md §3.3): requests park in the worker server, a reader turns them
into rows, the user pipeline computes a reply column, the sink routes
replies back, and each drained batch closes an epoch. The reference spreads
this across Spark's continuous-processing engine; here it is an explicit
background loop per host — the pipeline's ``transform`` still executes on
the TPU through the normal batching layer, so served traffic gets the same
large static-shape device batches as offline scoring.
"""

from __future__ import annotations

import logging
import threading
import time
import traceback
from typing import Callable, Dict, Optional

from ..core.dataframe import DataFrame
from ..observability import (counter as _metric_counter,
                             histogram as _metric_histogram)
from ..observability import tracing as _tracing
from ..reliability import get_injector as _get_injector
from ..reliability import record_retry as _record_retry
from .registry import get_registry as _get_registry
from .server import WorkerServer
from .source import HTTPSink, HTTPSource, parse_request

__all__ = ["ServingEngine"]

_log = logging.getLogger("mmlspark_tpu.serving")

_M_BATCH_ROWS = _metric_histogram(
    "mmlspark_serving_batch_rows",
    "Rows per drained serving batch (how well traffic coalesces)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096))
_M_BATCH_SECONDS = _metric_histogram(
    "mmlspark_serving_batch_seconds",
    "Wall-clock per drained batch: parse + transform + reply routing")
_M_BATCH_ERRORS = _metric_counter(
    "mmlspark_serving_batch_errors_total",
    "Serving batches whose transform raised (every row answered 500)")


class ServingEngine:
    """Run ``transform_fn`` (typically ``pipeline_model.transform``) over
    incoming HTTP requests.

    ``schema`` maps JSON body fields to column types; ``reply_col`` names the
    column whose values are JSON-encoded back to the caller.

    ``warm_up`` is the pre-serve compile hook: a zero-arg callable (typically
    ``model.warm_up`` or a ``functools.partial`` over it) invoked in
    :meth:`start` before any dispatcher thread begins draining requests, so
    the first request of each padding bucket never eats an XLA compile stall.
    A warm-up failure is logged, not fatal — serving starts cold rather than
    not at all.

    Multi-model dispatch: ``transform_fn`` may also be a dict mapping model
    NAME → transform. Requests carrying ``X-Mmlspark-Model`` resolve to a
    ``name@version`` through the :class:`~.registry.ModelRegistry` at
    ingest; each drained batch is then grouped by resolved version and each
    group dispatched to that version's registered handle (so a canary or
    shadow version actually exercises its own code), falling back to the
    dict entry for the name, then to ``"default"``. Versions are registered
    via :meth:`register_model` (which delegates to the process-global
    registry and runs the version's warm-up before it becomes routable).
    """

    def __init__(self, transform_fn: Callable[[DataFrame], DataFrame],
                 schema: Optional[Dict[str, type]] = None,
                 reply_col: str = "reply",
                 host: str = "127.0.0.1", port: int = 0, api_path: str = "/",
                 max_batch: int = 1024, poll_timeout: float = 0.05,
                 reply_timeout: float = 60.0, n_dispatchers: int = 1,
                 journal_path: Optional[str] = None,
                 transport: str = "threaded",
                 warm_up: Optional[Callable[[], object]] = None,
                 device_ingest: Optional[list] = None,
                 tuning: str = "", tuned_models: Optional[list] = None):
        self.transform_fn = transform_fn
        self.warm_up = warm_up
        if tuning not in ("", "auto"):
            raise ValueError(f"tuning must be '' or 'auto', got {tuning!r}")
        #: "auto" switches every model in ``tuned_models`` to store-driven
        #: tuning before warm-up, so the served pipeline runs (and its
        #: warm-up compiles) the measured config, not the Param defaults
        self.tuning = tuning
        self.tuned_models = list(tuned_models or [])
        self.schema = schema
        self.reply_col = reply_col
        #: columns staged device-resident right after parse, so every stage
        #: of the served pipeline reads them on device and the batch pays
        #: one ingest h2d total. DataFrame.device_put is idempotent: a batch
        #: whose inputs are already resident counts residency hits and is
        #: NOT re-staged.
        self.device_ingest = list(device_ingest or [])
        self.max_batch = max_batch
        self.poll_timeout = poll_timeout
        #: >1 overlaps batch formation/parse of one batch with the
        #: transform of another — the single-loop engine serialized them
        #: (the concurrency the reference gets from parallel Spark tasks)
        self.n_dispatchers = max(1, int(n_dispatchers))
        self.server = WorkerServer(host, port, api_path,
                                   reply_timeout=reply_timeout,
                                   journal_path=journal_path,
                                   transport=transport)
        self.source = HTTPSource(self.server)
        self.sink = HTTPSink(self.server, reply_col=self.reply_col)
        self._stop = threading.Event()
        self._threads: list = []

    @property
    def address(self) -> str:
        return self.server.address

    def register_model(self, name: str, version: str,
                       transform_fn: Callable[[DataFrame], DataFrame],
                       warm_up: Optional[Callable[[], object]] = None,
                       **kwargs):
        """Register ``name@version`` with the process-global registry,
        using ``transform_fn`` as the version's handle — the per-version
        dispatch target for batches this engine drains. Keyword args
        (``canary_percent``, ``shadow_percent``, ``block``, ...) pass
        through to :meth:`~.registry.ModelRegistry.load`."""
        return _get_registry().load(name, version, handle=transform_fn,
                                    warm_up=warm_up, **kwargs)

    def _dispatch_groups(self, parsed: DataFrame, ids):
        """Split a drained batch by resolved model version. Returns
        ``[(fn, sub_parsed, sub_ids), ...]``; ``fn`` is None for rows
        naming a model nothing serves (answered 404 by the caller). The
        single-model fast path (plain callable, no versioned rows) is a
        single zero-copy group."""
        labels = [self.server.model_label(r) for r in ids]
        if not isinstance(self.transform_fn, dict) \
                and not any(labels):
            return [(self.transform_fn, parsed, ids)]
        registry = _get_registry()
        fns: Dict[int, object] = {}
        rows: Dict[int, list] = {}
        for i, label in enumerate(labels):
            fn = None
            if label:
                handle = registry.handle_for(label)
                if callable(handle):
                    fn = handle
            if fn is None:
                name = (label or "default").partition("@")[0]
                if isinstance(self.transform_fn, dict):
                    fn = (self.transform_fn.get(name)
                          or self.transform_fn.get("default"))
                else:
                    fn = self.transform_fn
            key = id(fn)
            fns[key] = fn
            rows.setdefault(key, []).append(i)
        return [(fns[key],
                 parsed.take(idxs),
                 [ids[i] for i in idxs])
                for key, idxs in rows.items()]

    def start(self) -> "ServingEngine":
        if self.tuning == "auto":
            for m in self.tuned_models:
                try:
                    m.set(tuning="auto")
                except Exception:
                    _log.error("model %r rejected tuning='auto':\n%s",
                               getattr(m, "uid", m), traceback.format_exc())
        if self.warm_up is not None:
            try:
                self.warm_up()
            except Exception:
                _log.error("pre-serve warm-up failed (serving starts cold):"
                           "\n%s", traceback.format_exc())
        for i in range(self.n_dispatchers):
            t = threading.Thread(
                target=self._loop, daemon=True,
                name=f"serving-engine-{self.server.port}-{i}")
            t.start()
            self._threads.append(t)
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            df = self.source.read_batch(self.max_batch, self.poll_timeout)
            if len(df) == 0:
                continue
            ids = df["id"]
            _M_BATCH_ROWS.observe(len(df))
            # a drained batch coalesces many requests; the batch's spans
            # attach under the FIRST traced request's root (one concrete
            # trace showing the whole batch beats N duplicated subtrees),
            # with the co-batched count recorded as an attribute
            root = next((s for s in (self.server.trace_span(r) for r in ids)
                         if s is not None), None)
            t0 = time.perf_counter()
            with _tracing.activate(root), \
                    _tracing.start_span("engine.batch", rows=len(df)):
                try:
                    parsed = parse_request(df, self.schema)
                except Exception:
                    _M_BATCH_ERRORS.inc()
                    _tracing.add_event("batch_error", stage="parse")
                    _log.error("serving batch parse failed:\n%s",
                               traceback.format_exc())
                    for rid in ids:
                        self.server.reply_json(
                            rid, {"error": "internal error"}, status=500)
                    _M_BATCH_SECONDS.observe(time.perf_counter() - t0)
                    self.server.commit_epoch()
                    continue
                parsed = self._stage_ingest(parsed)
                for fn, sub, sub_ids in self._dispatch_groups(parsed, ids):
                    if fn is None:
                        for rid in sub_ids:
                            self.server.reply_json(
                                rid, {"error": "unknown model"},
                                status=404)
                        continue
                    if not self._run_batch(sub, sub_ids, fn):
                        # graceful degradation: a whole-batch failure is
                        # often OOM-shaped (too many rows in one device
                        # batch) — retry ONCE at half size before failing
                        # rows individually
                        if len(sub_ids) > 1:
                            mid = (len(sub_ids) + 1) // 2
                            splits = ((range(0, mid), sub_ids[:mid]),
                                      (range(mid, len(sub_ids)),
                                       sub_ids[mid:]))
                            for rows, half_ids in splits:
                                _record_retry("engine_batch", 1, 0.0,
                                              "batch_error")
                                if not self._run_batch(sub.take(rows),
                                                       half_ids, fn):
                                    self._fail_rows(half_ids)
                        else:
                            self._fail_rows(sub_ids)
                _M_BATCH_SECONDS.observe(time.perf_counter() - t0)
            self.server.commit_epoch()

    def _stage_ingest(self, parsed: DataFrame) -> DataFrame:
        """Stage ``device_ingest`` columns once per batch (idempotent:
        already-resident inputs count hits and move no bytes); a staging
        failure degrades to host-fed serving rather than failing the
        batch."""
        names = [c for c in self.device_ingest if c in parsed]
        if not names:
            return parsed
        try:
            return parsed.device_put(names)
        except Exception:
            _log.error("device ingest staging failed (host-fed batch):\n%s",
                       traceback.format_exc())
            return parsed

    def _fail_rows(self, ids) -> None:
        for rid in ids:
            self.server.reply_json(rid, {"error": "internal error"},
                                   status=500)

    def _run_batch(self, parsed: DataFrame, ids,
                   transform_fn: Optional[Callable] = None) -> bool:
        """Transform + route one (sub-)batch; False when the transform or
        sink raised (rows unanswered — the caller decides retry vs 500).
        ``transform_fn`` overrides the engine default (per-version
        dispatch)."""
        try:
            injector = _get_injector()
            if injector.enabled:
                injector.fire("device_run")
            fn = transform_fn if transform_fn is not None \
                else self.transform_fn
            out = fn(parsed)
            self.sink.write_batch(out)
            # rows the transform dropped (filters etc.) must still be
            # answered, or their CachedRequests leak in the routing table
            surviving = set(out["id"]) if "id" in out else set()
            for rid in ids:
                if rid not in surviving:
                    self.server.reply_json(
                        rid, {"error": "row dropped by pipeline"},
                        status=400)
            return True
        except Exception:
            _M_BATCH_ERRORS.inc()
            _tracing.add_event("batch_error", rows=len(ids))
            _log.error("serving batch failed:\n%s", traceback.format_exc())
            return False

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        self.server.close()

    def __enter__(self) -> "ServingEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
