"""Continuous batching for autoregressive decoding — LLM serving on TPU.

Beyond reference parity: SynapseML's serving answers one request with one
stateless transform (``HTTPSourceV2.scala:476-697``); an autoregressive
model needs *stateful* multi-step service, and naive request-at-a-time
decoding leaves the chip >90% idle at batch 1. The standard fix
(Orca/vLLM-style continuous batching) is rebuilt here the TPU way:

* a **static slot pool** — the KV-cache is a fixed (slots, heads, max_len,
  head_dim) buffer per layer, so XLA compiles exactly TWO programs (batched
  prefill + one ragged decode step) no matter how requests arrive;
* **per-slot positions** (``decode_step_ragged``) — every occupied slot
  advances at its own depth in the same compiled step, so new requests
  join mid-flight without draining the batch ("iteration-level
  scheduling");
* **prefill/decode split** (``prefill_cache``) — prompts run as ONE causal
  forward (MXU-friendly O(P) attention), then drop into a slot and decode
  incrementally;
* host-side bookkeeping only touches (slots,) vectors per tick — the
  device→host traffic per emitted token is a few hundred bytes, which is
  what the tunnel-dominated profile (BASELINE.md) wants;
* **prefill-ahead** (``prefill_ahead=N``) — while every slot is occupied,
  waiting prompts prefill in the background and park their KV rows on
  device, so a retiring wave re-fills with one insert dispatch instead of
  paying prefill + a first-token round-trip on the admission critical
  path (first tokens ride the drain pipeline like decode blocks).

The KV cache is **paged** (PagedAttention, vLLM): the physical cache is a
pool of fixed-size pages (`serving/kv_pool.py`) and each slot owns a block
table mapping its logical positions to physical pages, so

* a request pins pages for the tokens it can actually produce (prompt +
  max_new + speculative headroom), not a worst-case ``max_len`` region —
  short requests stop stranding HBM;
* common-prompt prefixes share PHYSICAL pages across requests
  (copy-on-write: only the boundary page is copied), replacing the old
  snapshot-and-recopy prefix cache;
* retiring requests return pages to a min-heap free list; when the live
  span drifts past the defrag threshold, one device gather compacts it.

Attention still runs the exact contiguous math: every step gathers a
slot's pages into the familiar dense layout and calls the same ragged
kernels (``decode_step_paged`` is bitwise-equal to ``decode_step_ragged``
by construction), so greedy outputs stay request-identical to
:func:`generate_cached`.

**Chunked prefill** (Orca-style iteration-level scheduling): prompts
longer than ``prefill_chunk`` admit immediately but prefill in
fixed-budget windows interleaved with decode ticks — a 4k-token prompt
no longer freezes every live stream, bounding p99 decode-step latency.
A ``KVAutotuner`` (optional, ``autotune=True``) closes the loop, walking
speculative gamma with the measured acceptance rate and the chunk budget
with live slot occupancy.
"""

import functools
import threading
import time
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..observability import (charge as _ledger_charge,
                             counter as _metric_counter,
                             gauge as _metric_gauge,
                             get_ledger as _get_ledger,
                             histogram as _metric_histogram,
                             log_event as _log_event,
                             resolve_context as _resolve_cost_ctx,
                             watch as _watch)
from ..observability import tracing as _tracing
from ..observability.slo import get_tracker as _slo_tracker
from ..reliability import get_injector as _get_injector
from ..reliability.lock_sanitizer import new_lock
from ..utils.profiling import span as _prof_span
from ..models.zoo.transformer import (TransformerConfig,
                                      _warp_scaled_rows,
                                      decode_step_ragged,
                                      decode_step_paged,
                                      decode_window_paged,
                                      paged_scatter_rows,
                                      prefill_cache, shardings_for)
from ..ops.padding import bucket_size
from ..ops.paged_attention import (resolve_impl as _resolve_paged_attn,
                                   _auto_interpret as _pa_auto_interpret)
from ..parallel.collective_audit import audit_program as _audit_program
from ..parallel.mesh import mesh_shape
from .kv_pool import (KVAutotuner, PagedKVPool, PoolExhausted,
                      prefix_hash as _prefix_hash)

_M_DRAIN_SECONDS = _metric_histogram(
    "mmlspark_continuous_drain_seconds",
    "Host fetch latency of one outstanding (k, S) token block — the only "
    "host<->device sync on the decode path")
_M_LIVE_SLOTS = _metric_gauge(
    "mmlspark_continuous_live_slots",
    "Occupied decode slots at the latest step (batch size on device)")
_M_PREFILLS = _metric_counter(
    "mmlspark_continuous_prefills_total",
    "Full prompt prefills executed (grouped prefills count once)")
_M_PREFIX_HITS = _metric_counter(
    "mmlspark_continuous_prefix_hits_total",
    "Prompts served from the prefix cache via a suffix window")


class _Request:
    __slots__ = ("rid", "prompt", "max_new", "tokens", "done", "event",
                 "submitted_at", "first_token_at", "finished_at",
                 "temperature", "top_k", "top_p", "seed",
                 "prefix_key", "prefix_len", "error",
                 "cost_cls", "cost_trace",
                 "session_id", "pre_emitted", "journaled")

    def __init__(self, rid, prompt, max_new, temperature=0.0, top_k=0,
                 top_p=1.0, seed=0):
        self.rid = rid
        self.prompt = prompt
        self.max_new = max_new
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = int(seed)
        self.prefix_key: Optional[str] = None
        self.prefix_len: Optional[int] = None
        self.error: Optional[Exception] = None
        self.tokens: List[int] = []
        self.done = False
        self.event = threading.Event()
        self.submitted_at = time.perf_counter()
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        # cost-ledger workload class + trace, captured at submit time
        # (engine-thread ticks run outside the request's trace context)
        self.cost_cls, self.cost_trace = _resolve_cost_ctx()
        #: durable-session identity (journal key; defaults to the rid)
        self.session_id: str = str(rid)
        #: tokens emitted by a PREVIOUS incarnation of this session — a
        #: restored request only generates the remainder; callers read the
        #: full completion via ``ContinuousDecoder.session_result``
        self.pre_emitted: List[int] = []
        #: how many of ``tokens`` have reached the journal tail
        self.journaled = 0


def _sample_rows(logits, temp, top_k, top_p, keys):
    """Per-ROW-parameter version of ``transformer._sample_logits``: each of
    the (S, V) rows carries its own temperature/top_k/top_p and PRNG key
    (requests in one slot pool sample independently). Row-for-row equal to
    ``_sample_logits`` run on that row alone with scalar params."""
    greedy = jnp.argmax(logits, axis=-1)
    scaled = logits / jnp.maximum(temp, 1e-6)[:, None]
    filtered = _warp_scaled_rows(scaled, top_k, top_p)
    sampled = jax.vmap(jax.random.categorical)(keys, filtered)
    return jnp.where(temp <= 0.0, greedy, sampled).astype(jnp.int32)


# ---- compiled-program factories (process-wide, config-keyed) ----
# Every decode-path program is a pure function of STATIC configuration
# (hashable scalars + the NamedTuple model configs) and its array
# arguments, so ``lru_cache`` makes each jitted callable a process-wide
# singleton per configuration: N engines with the same shapes (hot
# reloads, A/B pools, a test suite's many tiny engines) trace and
# compile every program ONCE instead of N times. Donation composes —
# each call donates its own argument buffers, never another engine's.

@functools.lru_cache(maxsize=None)
def _tick_program(cfg, page, Lc, k, eos, sample, donate, attn="kernel",
                  mesh=None, slot_axis=None, head_axis=None,
                  kv_dtype=None):
    """The decode tick: k paged steps fused in one lax.scan. ``attn``
    (part of the cache key — the impl is baked in at trace time) selects
    the Pallas paged-attention kernel or the gather fallback. ``mesh``
    (a hashable jax Mesh: axis names + sizes + devices) plus the engine's
    slot/head axis names are part of the cache key too, so a sharded
    engine and a single-chip engine with otherwise-identical shapes never
    share a trace — the kernel mounts via shard_map under a mesh.
    ``kv_dtype`` ("int8"/"fp8"/None) likewise: the quantized and bf16
    data planes differ in buffer pytree structure AND kernel choice, and
    must never share a program."""
    eos_const = None if eos is None else jnp.int32(eos)

    def tick(params, tok, pos, active, bufs, bt, remaining,
             temp=None, topk=None, topp=None, key=None):
        def body(carry, _):
            tok, pos, active, bufs, remaining = carry
            logits, bufs = decode_step_paged(
                params, tok, pos, bufs, bt, cfg,
                page_size=page, length=Lc, active=active, impl=attn,
                mesh=mesh, slot_axis=slot_axis, head_axis=head_axis)
            if sample:
                # emit position is pos+1 — generate_cached's key
                # schedule (fold_in by absolute emit position), so
                # sampled outputs are request-for-request
                # identical to the offline generator
                folded = jax.vmap(jax.random.fold_in)(key, pos + 1)
                nxt = _sample_rows(logits.astype(jnp.float32),
                                   temp, topk, topp, folded)
            else:
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            nxt = jnp.where(active, nxt, tok)
            pos = jnp.where(active, pos + 1, pos)
            remaining = jnp.where(active, remaining - 1, remaining)
            fin = remaining <= 0
            if eos_const is not None:
                fin = fin | (nxt == eos_const)
            active = active & ~fin
            return (nxt, pos, active, bufs, remaining), nxt
        carry, toks = jax.lax.scan(
            body, (tok, pos, active, bufs, remaining), None, length=k)
        return (*carry, toks)

    return jax.jit(tick, donate_argnums=(1, 2, 3, 4, 6) if donate else ())


@functools.lru_cache(maxsize=None)
def _prefill_program(cfg, L):
    """Batched prompt prefill — one compile per padded prompt bucket."""
    def _prefill(params, ids, length):
        return prefill_cache(params, ids, length, cfg, L)

    return jax.jit(_prefill)


@functools.lru_cache(maxsize=None)
def _extend_program(cfg, page, L, donate, attn="kernel",
                    mesh=None, head_axis=None, kv_dtype=None):
    """Paged window extension: continue ONE slot's pages over a token
    window — the prefix-cache suffix path and chunked prefill share this
    single program (one compile per window bucket). The gather impl
    gathers at length L: the exact reduction length the old contiguous
    extension used, so greedy prefix-hit outputs stay identical; the
    kernel impl reads pages in place (f32-accumulation tolerance).
    Under a mesh only heads shard (slot_axis stays None: the extension
    operates on a single B=1 row, which cannot split over dp)."""
    def _extend(params, ids, start, bufs, bt_row):
        return decode_window_paged(params, ids, start, bufs, bt_row,
                                   cfg, page_size=page, length=L,
                                   active=None, impl=attn, mesh=mesh,
                                   slot_axis=None, head_axis=head_axis)

    return jax.jit(_extend, donate_argnums=(3,) if donate else ())


@functools.lru_cache(maxsize=None)
def _copy_pages_program(donate):
    """Boundary-page copy for copy-on-write prefix admission (at most
    one page per admission — compiles per copy count). Generic over the
    layer-dict keys: a quantized pool's ``k_scale``/``v_scale`` arrays
    copy through the same src/dst page indices as their values (page 0,
    dim 0, for every buffer), so CoW admission needs no quant-specific
    path."""
    def _copy(bufs, src, dst):
        return [{kk: c[kk].at[dst].set(c[kk][src])
                 for kk in c} for c in bufs]

    return jax.jit(_copy, donate_argnums=(0,) if donate else ())


@functools.lru_cache(maxsize=None)
def _compact_program(donate):
    """Defrag: permute the whole page dimension in one gather — every
    buffer in each layer dict (values AND scales: a quantized page is
    meaningless without its scale row, so they remap through the SAME
    permutation in the same dispatch)."""
    def _compact(bufs, perm):
        return [{kk: c[kk][perm] for kk in c} for c in bufs]

    return jax.jit(_compact, donate_argnums=(0,) if donate else ())


@functools.lru_cache(maxsize=None)
def _insert_group_program(page, donate, kv_dtype=None):
    """Group insert: ALL rows admitted from one prefill land in one
    compiled call (slots is a (g,) vector, g gets its own tiny program —
    bounded by max_slots), and their first tokens compute on device in
    the same batch, so admission costs ONE dispatch + ONE fetch instead
    of one sync per request (each ~RTT behind the tunnel). Target rows
    scatter into the PAGE POOL through ``page_rows`` (each row's physical
    pages; entries past a row's allocation map to the trash page); draft
    rows land in the contiguous draft slot pool. Either row list may be
    EMPTY — state-only activation for prefix hits and chunked prefills,
    whose K/V is already in the pages — each emptiness pattern is its
    own pytree structure, so jit compiles a handful of small variants,
    not one per call. row lists are NOT donated: rows arrive as slices
    of the prefill output and a copy of g rows is cheaper than the
    sync."""
    def _insert_group(bufs, d_cache, slots, rows_t, rows_d, page_rows,
                      tok, pos, active, remaining, firsts, lengths,
                      rems, sample_state, sample_rows):
        g = slots.shape[0]
        if len(rows_t):        # pytree STRUCTURE: static per variant
            bufs = paged_scatter_rows(bufs, rows_t, page_rows, page)
        for c, rc in zip(d_cache, rows_d):
            for kk in ("k", "v"):
                for i in range(g):            # g static: unrolled
                    c[kk] = jax.lax.dynamic_update_slice(
                        c[kk], rc[kk][i:i + 1], (slots[i], 0, 0, 0))
        tok = tok.at[slots].set(firsts)
        pos = pos.at[slots].set(lengths)
        active = active.at[slots].set(True)
        remaining = remaining.at[slots].set(rems)
        temp, topk, topp, key = sample_state
        rt, rk, rp, rkey = sample_rows
        sample_state = (temp.at[slots].set(rt), topk.at[slots].set(rk),
                        topp.at[slots].set(rp), key.at[slots].set(rkey))
        return (bufs, d_cache, tok, pos, active, remaining,
                sample_state)

    return jax.jit(_insert_group,
                   donate_argnums=(0, 1, 6, 7, 8, 9, 13) if donate else ())


@functools.lru_cache(maxsize=None)
def _first_tokens_program():
    """First emitted token for every prefilled row, on device: position
    P_i sampled with fold_in(key_i, P_i) — generate_cached's exact
    schedule (temp <= 0 rows reduce to argmax inside _sample_rows)."""
    def _first_tokens(logits, temps, topks, topps, keys, lengths):
        folded = jax.vmap(jax.random.fold_in)(keys, lengths)
        return _sample_rows(logits.astype(jnp.float32),
                            temps, topks, topps, folded)

    return jax.jit(_first_tokens)


@functools.lru_cache(maxsize=None)
def _quant_probe_program(kv_dtype):
    """Write-time quant-error probe: the relative RMS between the bf16
    prefill rows a quantized insert is about to scatter and their
    ``dequantize(quantize(.))`` roundtrip — exactly the delta between
    what the quantized kernel will read back and what the byte-exact
    bf16 oracle would have read. Returns ``(err_rms, ref_rms)`` so the
    host forms the scale-free ratio. One tiny program per kv_dtype."""
    from ..ops.kv_quant import dequantize_kv, kv_store_dtype, quantize_kv
    store = kv_store_dtype(kv_dtype)

    def _probe(rows):
        x = rows.astype(jnp.float32)
        q, s = quantize_kv(x, store)
        d = dequantize_kv(q, s) - x
        return (jnp.sqrt(jnp.mean(d * d)),
                jnp.sqrt(jnp.mean(x * x)))

    return jax.jit(_probe)


@functools.lru_cache(maxsize=None)
def _spec_tick_program(cfg, d_cfg, page, Lc, k_steps, eos, gamma,
                       sample, warp, donate, attn="kernel",
                       mesh=None, slot_axis=None, head_axis=None,
                       kv_dtype=None):
    """The speculative tick: k draft→verify rounds in one scan.

    Per round, the draft proposes gamma tokens per slot (gamma+1 ragged
    steps — the extra step writes the last proposal's K/V so the draft
    cache is hole-free under full acceptance); the target scores every
    slot's (pending + drafts) window in ONE ragged forward; each slot
    accepts its own longest valid prefix plus a final token. Greedy
    slots: proposals are draft argmaxes, acceptance is target-argmax
    match, the final token is the target's greedy choice — outputs
    request-identical to the plain greedy engine. Sampled slots
    (sample=True): proposals are draft SAMPLES, token x accepted with
    prob min(1, p_t(x)/p_d(x)), a rejection resamples from the
    normalized residual max(p_t − p_d, 0) — the speculative-sampling
    correction, so the output DISTRIBUTION exactly equals sampling from
    the target (bit-identity to the plain sampled engine is impossible:
    the procedures consume randomness differently; the per-slot contract
    is distributional). Per-slot acceptance means no batch-min
    truncation, so the zoo impl's accepted-at-cut case cannot arise: the
    accepted count IS each slot's true rejection point, and a rejected
    token can never be re-emitted (its residual mass is zero).
    Randomness is keyed by (request key, absolute emit position,
    purpose) — discarded tail draws never influence emitted state, so
    replays are never of identical inputs. Rejected-tail cache entries
    are stale by position and overwritten before any valid query sees
    them. Emission: a (k*(gamma+1), S) block where -1 marks unemitted
    lanes — the host drain skips negatives.

    gamma is a compile-time constant of the round structure, so the
    autotuner's gamma ladder memoizes one compiled program per
    (mode, gamma) — bounded by 3 × gamma_max entries. The TARGET cache
    is paged (verify gathers through the block table); the DRAFT cache
    stays a contiguous slot pool — a draft is small by construction and
    pays the gather for nothing."""
    eos_const = None if eos is None else jnp.int32(eos)

    def spec_tick(params, d_params, tok, pos, active, bufs,
                  bt, d_cache, remaining, temp=None, key=None,
                  topk=None, topp=None):
        idx = jnp.arange(gamma + 1)

        def keys_at(qpos, purpose):
            # (S,) keys at absolute emit positions qpos
            k1 = jax.vmap(jax.random.fold_in)(key, qpos)
            return jax.vmap(jax.random.fold_in, (0, None))(
                k1, purpose)

        def warm_logp(lg):
            # temp is (S,); lg is (S, V) or (S, W, V). The
            # top-k/top-p warp applies to TARGET and DRAFT
            # alike (rejection stays exact only under a
            # shared warp). Greedy rows may carry non-neutral
            # top_k/top_p values — harmless only because the
            # temp>0 masks discard every warped quantity for
            # them. The warp=False variant skips the
            # sort-based filter entirely — the host picks it
            # whenever no live row warps, keeping the
            # temperature-only hot path at one log_softmax.
            t = jnp.maximum(temp, 1e-6).reshape(
                (lg.shape[0],) + (1,) * (lg.ndim - 1))
            scaled = lg.astype(jnp.float32) / t
            if not warp:
                return jax.nn.log_softmax(scaled, -1)
            if lg.ndim == 2:
                warped = _warp_scaled_rows(scaled, topk, topp)
            else:
                s_, w_, v_ = scaled.shape
                warped = _warp_scaled_rows(
                    scaled.reshape(s_ * w_, v_),
                    jnp.repeat(topk, w_),
                    jnp.repeat(topp, w_)).reshape(s_, w_, v_)
            return jax.nn.log_softmax(warped, -1)

        def round_body(carry, _):
            (tok, pos, active, bufs, d_cache,
             remaining) = carry

            def dstep(c, i):
                dc, t = c
                lg, dc = decode_step_ragged(
                    d_params, t, pos + i, dc, d_cfg, active)
                nxt = jnp.argmax(lg, -1).astype(jnp.int32)
                if sample:
                    logp = warm_logp(lg)        # (S, V)
                    samp = jax.vmap(jax.random.categorical)(
                        keys_at(pos + i + 1, 1), logp)
                    nxt = jnp.where(temp > 0.0,
                                    samp.astype(jnp.int32),
                                    nxt)
                else:
                    logp = jnp.zeros((lg.shape[0], 1),
                                     jnp.float32)
                return ((dc, jnp.where(active, nxt, t)),
                        (nxt, logp))

            (d_cache, _), (props, d_logps) = jax.lax.scan(
                dstep, (d_cache, tok), jnp.arange(gamma + 1))
            drafts = jnp.moveaxis(props[:gamma], 0, 1)
            wtoks = jnp.concatenate([tok[:, None], drafts], 1)
            w_logits, bufs = decode_window_paged(
                params, wtoks, pos, bufs, bt, cfg,
                page_size=page, length=Lc, active=active, impl=attn,
                mesh=mesh, slot_axis=slot_axis, head_axis=head_axis)
            greedy = jnp.argmax(w_logits, -1).astype(jnp.int32)
            match = greedy[:, :gamma] == drafts
            if sample:
                t_logp = warm_logp(w_logits)    # (S, g+1, V)
                d_logp = jnp.moveaxis(d_logps[:gamma], 0, 1)
                lp_t = jnp.take_along_axis(
                    t_logp[:, :gamma], drafts[..., None],
                    -1)[..., 0]
                lp_d = jnp.take_along_axis(
                    d_logp, drafts[..., None], -1)[..., 0]
                us = jnp.stack(
                    [jax.vmap(jax.random.uniform)(
                        keys_at(pos + j + 1, 2))
                     for j in range(gamma)], axis=1)
                acc_s = (jnp.log(jnp.maximum(us, 1e-38))
                         < lp_t - lp_d)
                accepts = jnp.where(temp[:, None] > 0.0,
                                    acc_s, match)
            else:
                accepts = match
            k = jnp.sum(jnp.cumprod(
                accepts.astype(jnp.int32), -1), -1)   # (S,)
            final = jnp.take_along_axis(greedy, k[:, None],
                                        1)[:, 0]
            if sample:
                p_t_k = jnp.take_along_axis(
                    jnp.exp(t_logp),
                    k[:, None, None].repeat(
                        t_logp.shape[-1], 2)[:, :1], 1)[:, 0]
                d_logp_pad = jnp.concatenate(
                    [d_logp,
                     jnp.full((d_logp.shape[0], 1,
                               d_logp.shape[-1]),
                              -jnp.inf, jnp.float32)], 1)
                p_d_k = jnp.take_along_axis(
                    jnp.exp(d_logp_pad),
                    k[:, None, None].repeat(
                        d_logp.shape[-1], 2)[:, :1], 1)[:, 0]
                resid = jnp.maximum(p_t_k - p_d_k, 0.0)
                tot = jnp.sum(resid, -1, keepdims=True)
                resid = jnp.where(tot > 1e-30, resid / tot,
                                  p_t_k)
                resampled = jax.vmap(jax.random.categorical)(
                    keys_at(pos + k + 1, 3),
                    jnp.log(jnp.maximum(resid, 1e-38)))
                final = jnp.where(temp > 0.0,
                                  resampled.astype(jnp.int32),
                                  final)
            pad_drafts = jnp.concatenate(
                [drafts, drafts[:, -1:]], 1)
            cand = jnp.where(idx[None] < k[:, None],
                             pad_drafts, final[:, None])
            cnt = jnp.minimum(k + 1, remaining)
            if eos_const is not None:
                # truncate at the first emitted eos,
                # inclusive — sequential-emission semantics
                is_eos = ((cand == eos_const)
                          & (idx[None] < cnt[:, None]))
                cnt = jnp.where(jnp.any(is_eos, -1),
                                jnp.argmax(is_eos, -1) + 1,
                                cnt)
            cnt = jnp.where(active, cnt, 0)
            emit = jnp.where(idx[None] < cnt[:, None],
                             cand, -1)
            pos = pos + cnt
            remaining = remaining - cnt
            fin = remaining <= 0
            if eos_const is not None:
                fin = fin | jnp.any(emit == eos_const, -1)
            active = active & ~fin
            last = jnp.take_along_axis(
                cand, jnp.maximum(cnt - 1, 0)[:, None],
                1)[:, 0]
            tok = jnp.where(cnt > 0, last, tok)
            return ((tok, pos, active, bufs, d_cache,
                     remaining), emit.T)

        carry, emits = jax.lax.scan(
            round_body,
            (tok, pos, active, bufs, d_cache, remaining),
            None, length=k_steps)
        return (*carry, emits.reshape(-1, emits.shape[-1]))

    return jax.jit(
        spec_tick,
        donate_argnums=(2, 3, 4, 5, 7, 8) if donate else ())


class ContinuousDecoder:
    """Slot-pool continuous-batching engine over the zoo decoder.

    ``submit()`` is thread-safe and returns a ticket; ``step()`` runs one
    engine tick (admit waiting prompts into free slots, one ragged decode
    step over ALL occupied slots, retire finished rows). Call ``step()``
    from a driver loop — or ``serve_forever()`` on a background thread.

    Greedy decoding (the parity-testable mode): each request's output is
    bit-identical to running :func:`generate_cached` on its prompt alone —
    continuous batching changes THROUGHPUT, never results.
    """

    def __init__(self, params: Dict, cfg: TransformerConfig, *,
                 max_slots: int = 4, max_len: int = 256,
                 eos_id: Optional[int] = None,
                 mesh: Optional[Mesh] = None,
                 prefix_cache_size: int = 8,
                 steps_per_dispatch: int = 1,
                 pipeline_depth: int = 2,
                 prefill_ahead: int = 0,
                 draft_params: Optional[Dict] = None,
                 draft_cfg: Optional[TransformerConfig] = None,
                 gamma: int = 4,
                 page_size: int = 16,
                 prefill_chunk: int = 256,
                 kv_pages: Optional[int] = None,
                 autotune: bool = False,
                 defrag_threshold: Optional[int] = None,
                 paged_attn: Optional[str] = None,
                 kv_dtype: Optional[str] = None,
                 quant_probe: int = 64,
                 slo_model: str = "default",
                 journal=None):
        if cfg.moe_experts:
            raise ValueError("continuous decoding does not support MoE")
        if not cfg.causal:
            raise ValueError("ContinuousDecoder needs cfg.causal=True")
        #: speculative mode: a draft model proposes gamma greedy tokens per
        #: round PER SLOT; the target verifies all slots' windows in one
        #: ragged forward and each slot advances by its own accepted
        #: prefix + bonus — 1..gamma+1 tokens per round for ~one target
        #: step's cost. Greedy outputs stay request-identical to the plain
        #: engine (accepted tokens ARE the target's greedy choices).
        self._spec = draft_params is not None
        if self._spec:
            if draft_cfg is None:
                raise ValueError("draft_params without draft_cfg")
            if draft_cfg.vocab != cfg.vocab:
                raise ValueError("draft and target must share a vocabulary")
            if not draft_cfg.causal or draft_cfg.moe_experts:
                raise ValueError("draft must be causal and dense")
        if gamma < 1:
            # validated even without a draft: a stored bad value would
            # otherwise only explode when a draft is added later
            raise ValueError("gamma must be >= 1")
        self._gamma = int(gamma)
        #: autotuned gamma walks a ladder up to gamma_max; the cache
        #: headroom, page counts and retirement horizon all size for the
        #: CEILING so a mid-stream gamma bump never outgrows a slot's
        #: pages. Without autotune the ceiling IS gamma — sizes (and so
        #: compiled programs and bitwise behavior) are unchanged.
        self._gamma_max = (max(self._gamma, 8)
                           if (autotune and self._spec) else self._gamma)
        self._d_cfg = draft_cfg
        if cfg.position == "learned" and max_len > cfg.max_len:
            # positions beyond the learned table would CLAMP (JAX gather
            # semantics) and silently diverge from generate_cached
            raise ValueError(
                f"max_len {max_len} exceeds the learned position table "
                f"cfg.max_len {cfg.max_len}")
        self._cfg = cfg
        self._S = int(max_slots)
        self._L = int(max_len)
        self._eos = eos_id
        self._mesh = mesh
        if steps_per_dispatch < 1:
            raise ValueError("steps_per_dispatch must be >= 1")
        #: decode steps fused into one device dispatch (lax.scan). Behind a
        #: network-attached chip every dispatch pays ~RTT, so the
        #: single-step engine emits ~1/RTT tokens/s no matter how fast the
        #: chip is; k steps per dispatch cut the host syncs k-fold.
        #: Per-slot retirement (eos / max_new) moves INSIDE the scan so
        #: outputs stay token-identical; admission granularity coarsens to
        #: one dispatch (a freed slot re-fills at the next host tick).
        self._k = int(steps_per_dispatch)
        #: dispatches allowed in flight before the oldest token block is
        #: fetched. The fetch is the only host↔device sync on the decode
        #: path; at depth 0 every tick blocks ~RTT + device time (the r4
        #: ceiling: ~10 ticks/s over the tunnel no matter how fast the
        #: chip). With depth d the device runs ticks back-to-back while
        #: the host drains blocks d dispatches behind — outputs are
        #: token-identical, only admission of a freed slot lags by ≤ d
        #: ticks. Device-side retirement (in-scan remaining/eos) is what
        #: makes the lag safe: a done slot stays inactive on device no
        #: matter how far the host view trails.
        if pipeline_depth < 0:
            raise ValueError("pipeline_depth must be >= 0")
        self._depth = int(pipeline_depth)
        #: (device token block (rows, cols), {col: (slot, request)} at
        #: dispatch time) per outstanding dispatch, oldest first. Tick
        #: blocks are (k, S) with col == slot; admission first-token
        #: blocks are (1, g) with col == position-in-group.
        self._pending: List[tuple] = []
        #: prefill-ahead staging budget in ROWS (0 disables). While every
        #: slot is occupied, waiting prompts prefill in the background and
        #: their (logits, KV rows) park on device, so a retiring wave
        #: re-fills with ONE insert dispatch instead of paying the
        #: prefill on the admission critical path. Each staged row holds a
        #: full (heads, max_len, head_dim) KV row per layer — budget is
        #: HBM, spend deliberately.
        if prefill_ahead < 0:
            raise ValueError("prefill_ahead must be >= 0")
        self._stage_cap = int(prefill_ahead)
        #: staged units: [requests, logits, row_cache, next-offset]
        self._staged: List[list] = []
        params = jax.tree.map(jnp.asarray, params)
        hd = cfg.d_model // cfg.heads
        # speculative headroom: a verify window optimistically WRITES all
        # gamma+1 positions even when fewer remain before max_new; slot
        # allocations carry gamma_max+1 spare positions so the tail write
        # never clamps onto live entries. Prefill rows stay _L long —
        # their missing tail is garbage the key mask never exposes.
        self._Lc = self._L + (self._gamma_max + 1 if self._spec else 0)
        if mesh is None:
            self._params = jax.device_put(params)
            cache_sharding = state_sharding = pool_sharding = None
            slot_axis = head_axis = None
        else:
            # tensor-parallel serving: Megatron layout on the params
            # (shardings_for), KV heads over "tp", slots over "dp" when
            # present and divisible — GSPMD propagates through the ragged
            # step exactly as it does through transformer_apply
            tp = mesh.shape.get("tp", 1)
            if cfg.heads % tp:
                raise ValueError(
                    f"heads {cfg.heads} not divisible by mesh tp={tp}")
            dp = mesh.shape.get("dp", 1)
            slot_axis = "dp" if (dp > 1 and self._S % dp == 0) else None
            # a dp-only mesh is legal (request data parallelism without
            # tensor parallelism) — only name axes the mesh actually has
            head_axis = "tp" if "tp" in mesh.axis_names else None
            cache_sharding = NamedSharding(
                mesh, P(slot_axis, head_axis, None, None))
            # page pools shard over heads only: the page dimension is a
            # shared allocator arena, not a per-request batch axis
            pool_sharding = NamedSharding(
                mesh, P(None, head_axis, None, None))
            state_sharding = NamedSharding(mesh, P())
            # dp-only mesh: replicate params (shardings_for names "tp")
            self._params = jax.device_put(
                params, shardings_for(params, mesh)
                if head_axis else state_sharding)
        #: mesh identity for program cache keys + tuning stamps: the mesh
        #: itself (hashable — axis names, sizes, devices), the resolved
        #: shard axes, and the canonical "dp4xtp2"-style shape string
        self._mesh = mesh
        self._slot_axis = slot_axis
        self._head_axis = head_axis
        self._mesh_shape = mesh_shape(mesh)
        if self._spec:
            d_params = jax.tree.map(jnp.asarray, draft_params)
            # the draft is small by construction: replicate it on a mesh
            # rather than constraining its head count to tp
            self._d_params = (jax.device_put(d_params) if mesh is None
                              else jax.device_put(
                                  d_params, NamedSharding(mesh, P())))
            d_hd = draft_cfg.d_model // draft_cfg.heads
            self._d_cache_shape = (self._S, draft_cfg.heads, self._Lc, d_hd)

        def _zeros(shape_, dtype, sharded=False, fill=None):
            z = (jnp.zeros(shape_, dtype) if fill is None
                 else jnp.full(shape_, fill, dtype))
            if mesh is None:
                return z
            return jax.device_put(
                z, cache_sharding if sharded else state_sharding)

        self._zeros = _zeros

        # ---- the paged KV pool + block tables ----
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        if prefill_chunk < 8:
            # the pad-bucket floor; a sub-bucket budget would chunk every
            # prompt into windows the bucketing immediately re-inflates
            raise ValueError("prefill_chunk must be >= 8")
        #: paged-attention implementation: the Pallas kernel (default)
        #: reads K/V pages in place through the block table; "gather"
        #: keeps PR 7's gather-then-ragged path (bitwise vs contiguous).
        #: Resolved ONCE here and threaded into every compiled-program
        #: cache key — the env knob must not leak into shared programs.
        impl = _resolve_paged_attn(paged_attn)
        # under a mesh the kernel mounts via shard_map (heads over tp,
        # slots over dp) — ops/paged_attention.py runs the unchanged
        # per-shard kernel over each heads/tp slice, so no downgrade:
        # sharded engines and single-chip engines run the same impl
        self._attn_impl = impl
        #: quantized KV data plane: "int8"/"fp8" store quantized pages +
        #: per-position per-head scales; None keeps bf16 pages (the
        #: byte-exact oracle). Resolved ONCE and threaded into every
        #: compiled-program cache key.
        from ..ops.kv_quant import kv_store_dtype as _kv_store_dtype
        from ..ops.kv_quant import resolve_kv_dtype as _resolve_kv_dtype
        self._kv_dtype = _resolve_kv_dtype(kv_dtype)
        kv_value_dtype = _kv_store_dtype(self._kv_dtype) or cfg.dtype
        if quant_probe < 0:
            raise ValueError("quant_probe must be >= 0")
        self._quant_probe = int(quant_probe) if self._kv_dtype else 0
        self._quant_inserts = 0
        self._slo_model = str(slo_model)
        #: optional ServingJournal for durable sessions: a ``sess`` record
        #: at submit (write-ahead — a failed append errors the submit, not
        #: the engine), one batched ``tail`` record per drain tick, and a
        #: ``sess_end`` at completion. None = sessions die with the process.
        self._journal = journal
        self._quant_probe_j = (_quant_probe_program(self._kv_dtype)
                               if self._quant_probe else None)
        if impl == "kernel" and not _pa_auto_interpret():
            # real TPU: the page dimension sits in the kernel's sublane
            # slot — round the page size up to the tile of the dtype the
            # pages are STORED in (int8 pages tile at 32, bf16 at 16)
            # (transparent to allocation accounting; interpret-mode CI
            # keeps the requested size so test pool shapes are unchanged).
            # The rounding is per-SHARD invariant: sharding splits heads,
            # not the page dimension, so the same aligned size serves
            # every mesh shape
            page_size = PagedKVPool.kernel_aligned_page_size(
                page_size, kv_value_dtype)
        self._page = int(page_size)
        #: block-table width: logical pages per slot at full cache length
        self._P_max = -(-self._Lc // self._page)
        if kv_pages is None:
            # every slot at worst case, plus slack so prefix sharing and
            # admission bursts don't immediately hit the exhaustion path
            kv_pages = (1 + self._S * self._P_max
                        + max(self._P_max, self._S))
        if kv_pages < 1 + self._P_max:
            raise ValueError(
                f"kv_pages {kv_pages} cannot hold one full-length slot "
                f"({self._P_max} pages + the trash page)")

        scale_sharding = (None if pool_sharding is None
                          else NamedSharding(mesh, P(None, head_axis, None)))

        def _pool_buffer(shape_, dtype):
            z = jnp.zeros(shape_, dtype)
            if pool_sharding is None:
                return z
            # 4D (N, H, page, hd) value pools vs 3D (N, H, page) scale
            # pools — both shard heads over tp, nothing else
            return jax.device_put(
                z, pool_sharding if len(shape_) == 4 else scale_sharding)

        self._kv = PagedKVPool(cfg, num_pages=int(kv_pages),
                               page_size=self._page,
                               kv_dtype=self._kv_dtype,
                               make_buffer=_pool_buffer,
                               sharding=pool_sharding)
        self._chunk = int(prefill_chunk)
        self._defrag_thr = (max(1, self._kv.num_pages // 4)
                            if defrag_threshold is None
                            else max(1, int(defrag_threshold)))
        self._tuner = (KVAutotuner(gamma=self._gamma,
                                   gamma_max=self._gamma_max,
                                   chunk=self._chunk,
                                   chunk_min=min(32, self._chunk),
                                   chunk_max=max(1024, self._chunk),
                                   depth=self._depth,
                                   depth_min=min(1, self._depth),
                                   depth_max=max(4, self._depth))
                       if autotune else None)
        self._reset_device_state()
        self._slot_req: List[Optional[_Request]] = [None] * self._S
        self._waiting: List[_Request] = []
        self._lock = new_lock(                  # guards _waiting/_next_rid
            "serving.continuous.ContinuousDecoder._lock")
        self._engine_lock = new_lock(           # serializes step/cancel_all
            "serving.continuous.ContinuousDecoder._engine_lock")
        self._next_rid = 0
        self._stop = threading.Event()

        # ---- the compiled programs ----
        # donate the KV cache (and the small state vectors) so XLA updates
        # it in place — without donation every tick copies the full
        # (slots, heads, max_len, hd) × layers × {k,v} buffer set, doubling
        # peak cache HBM and its bandwidth on the hot path. CPU (the test
        # backend) doesn't implement donation; gate to keep tests quiet.
        donate = jax.default_backend() != "cpu"

        # ---- the decode tick: k ragged steps fused in one lax.scan ----
        # (k = steps_per_dispatch; k=1 is the same program with a length-1
        # scan). Per-slot retirement — the remaining counter and eos —
        # runs INSIDE the scan, mirroring ``_note_token`` exactly, so a
        # slot that finishes mid-scan stops advancing and the emitted
        # streams are identical to k single-step ticks; the host reads the
        # whole (k, S) token block in one fetch. One body serves greedy
        # and sampled (the only difference is how ``nxt`` is chosen).
        page, Lc = self._page, self._Lc

        # The block table rides every tick as a NON-donated, non-carried
        # argument: the scan body reads it (gather + writeback routing)
        # but never changes it — pages are remapped host-side between
        # dispatches, and the engine re-binds self._bt outside jit.
        # every cached program mounts through the collective auditor —
        # identity when MMLSPARK_TPU_COLLECTIVE_AUDIT is unset, else the
        # compiled HLO's collectives are counted per argument signature
        # and diffed against tools/tpulint/collective_budget.json
        self._tick = _audit_program("tick", _tick_program(
            cfg, page, Lc, self._k, self._eos, False, donate,
            self._attn_impl, mesh, slot_axis, head_axis,
            self._kv_dtype))
        self._tick_sampled = _audit_program("tick_sampled", _tick_program(
            cfg, page, Lc, self._k, self._eos, True, donate,
            self._attn_impl, mesh, slot_axis, head_axis,
            self._kv_dtype))
        # per-call KV HBM traffic of one full sweep over the cache at
        # worst-case length, in the bytes the pool ACTUALLY stores — the
        # quantized plane shrinks this ~2x (int8 values + bf16 scales vs
        # bf16 values), which is exactly what bench's
        # hbm_bytes_saved_per_step counter-asserts. Under the gather impl
        # this is also what materializing contiguous K/V reads from the
        # pool (feeding mmlspark_kvpool_gather_bytes_total); the kernel
        # impl reads the same pages in place.
        self._gather_bytes_tick = (self._S * Lc *
                                   self._kv.bytes_per_position())
        self._gather_bytes_extend = (self._L *
                                     self._kv.bytes_per_position())
        #: most tokens one dispatch can emit per slot (the retirement
        #: horizon unit): k plain steps, or k rounds × (gamma+1) spec —
        #: sized at the autotune CEILING so the horizon stays an upper
        #: bound whatever gamma the tuner is running
        self._max_per_dispatch = (self._k * (self._gamma_max + 1)
                                  if self._spec else self._k)

        # ---- the speculative tick (see _spec_tick_program) ----
        if self._spec:
            d_cfg = self._d_cfg

            self._spec_ticks: Dict[tuple, object] = {}

            def _spec_tick_for(mode: str, g: int):
                fn = self._spec_ticks.get((mode, g))
                if fn is None:
                    fn = _audit_program("spec_tick", _spec_tick_program(
                        cfg, d_cfg, page, Lc, self._k, self._eos, g,
                        sample=(mode != "greedy"),
                        warp=(mode == "warped"), donate=donate,
                        attn=self._attn_impl, mesh=self._mesh,
                        slot_axis=self._slot_axis,
                        head_axis=self._head_axis,
                        kv_dtype=self._kv_dtype))
                    self._spec_ticks[(mode, g)] = fn
                return fn

            self._spec_tick_for = _spec_tick_for

        # one compiled prefill per padded prompt bucket
        self._prefill = _audit_program("prefill",
                                       _prefill_program(cfg, self._L))
        if self._spec:
            # the draft pool prefills the same prompts (its cache must
            # hold the prompt K/V before it can propose)
            self._d_prefill = _audit_program(
                "draft_prefill", _prefill_program(self._d_cfg, self._L))

        # prefix-cache suffix extension + chunked prefill (one program)
        self._extend_paged = _audit_program("extend", _extend_program(
            cfg, page, self._L, donate, self._attn_impl, mesh,
            head_axis, self._kv_dtype))

        # copy-on-write boundary-page copy + defrag permutation
        self._copy_pages_j = _audit_program("copy_pages",
                                            _copy_pages_program(donate))
        self._compact_j = _audit_program("compact",
                                         _compact_program(donate))
        #: key → (prefix token copy, pool prefix hash, prefix length);
        #: the PAGES live in the pool's prefix registry — this host map
        #: adds the engine-facing key, LRU promotion and FIFO eviction
        self._prefix_store_cap = int(prefix_cache_size)
        #: observability: prefill vs prefix-hit counts (tests + ops)
        self.stats = {"prefills": 0, "prefix_hits": 0}

        # group insert + first tokens (see the module factories)
        self._insert_group_j = _audit_program(
            "insert_group", _insert_group_program(page, donate,
                                                  self._kv_dtype))
        self._first_tokens = _audit_program("first_tokens",
                                            _first_tokens_program())

    def _reset_device_state(self):
        """(Re)build every slot-pool device buffer — at construction and in
        :meth:`cancel_all` (post-failure the old, possibly-donated buffers
        must never be reused). Mesh shardings are re-applied here so a
        cancel on a tensor-parallel pool stays tensor-parallel. The page
        pool resets with everything else — the prefix registry's pages die
        with it, so the host prefix map is cleared too."""
        cfg = self._cfg
        self._kv.reset()
        self._bt_host = np.zeros((self._S, self._P_max), np.int32)
        self._bt = jnp.asarray(self._bt_host)
        self._slot_pages: List[Optional[List[int]]] = [None] * self._S
        #: slot → [request, prefill offset] for prompts mid-chunked-prefill
        #: (occupied but device-inactive until the final chunk activates)
        self._chunking: Dict[int, list] = {}
        #: recent chunk sizes in tokens (tests + bench assert the budget
        #: bound from this)
        self._chunk_trace: List[int] = []
        self._prefix_store: Dict[str, tuple] = {}
        if self._spec:
            dshape, dcfg = self._d_cache_shape, self._d_cfg
            self._d_cache = [{"k": self._zeros(dshape, dcfg.dtype),
                              "v": self._zeros(dshape, dcfg.dtype)}
                             for _ in range(dcfg.layers)]
        self._tok = self._zeros((self._S,), jnp.int32)
        self._pos = self._zeros((self._S,), jnp.int32)
        # tpulint: disable=TPU012 — every post-construction caller
        # (cancel_all) already holds _engine_lock; the other call site is
        # the constructor, before any engine thread exists
        self._active = self._zeros((self._S,), bool)
        #: tokens each slot may still emit (drives in-scan retirement for
        #: steps_per_dispatch > 1; maintained for k = 1 too)
        self._remaining = self._zeros((self._S,), jnp.int32)
        # per-slot sampling state (all-greedy pools never touch it: step()
        # dispatches the cheaper greedy tick when no slot samples)
        self._temp = self._zeros((self._S,), jnp.float32)
        self._topk = self._zeros((self._S,), jnp.int32)
        self._topp = self._zeros((self._S,), jnp.float32, fill=1.0)
        self._key = self._zeros((self._S, 2), jnp.uint32)

    # ---- client surface ----
    def submit(self, prompt_ids, max_new_tokens: int = 32, *,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0, seed: int = 0,
               prefix_key: Optional[str] = None,
               prefix_len: Optional[int] = None,
               session_id: Optional[str] = None,
               _journal_record: bool = True) -> _Request:
        """``prefix_key`` enables prefix caching (the shared-system-prompt
        pattern): the first request carrying a key prefills normally and
        snapshots its prompt's first ``prefix_len`` positions (default:
        the whole prompt); later requests with the same key — whose
        prompts MUST start with the stored tokens — skip recomputing the
        prefix and run one window forward over just the suffix. Greedy
        outputs are unchanged; only prefill cost drops."""
        prompt = np.asarray(prompt_ids, dtype=np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        if prompt.min() < 0 or prompt.max() >= self._cfg.vocab:
            # a traced gather would CLAMP out-of-range ids and generate
            # from a silently different prompt
            raise ValueError(
                f"token ids must be in [0, {self._cfg.vocab}); got range "
                f"[{prompt.min()}, {prompt.max()}]")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1 (the prefill "
                             "itself emits the first token)")
        if prompt.size + max_new_tokens > self._L:
            raise ValueError(
                f"prompt {prompt.size} + max_new {max_new_tokens} exceeds "
                f"cache max_len {self._L}")
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if top_k < 0 or temperature < 0.0:
            raise ValueError("top_k and temperature must be >= 0")
        if prefix_key is not None and not isinstance(prefix_key, str):
            # an unhashable key would TypeError inside the engine thread,
            # poisoning the batch instead of 400-ing this request
            raise ValueError(
                f"prefix_key must be a string, got {type(prefix_key).__name__}")
        if prefix_len is not None:
            if prefix_key is None:
                raise ValueError("prefix_len without prefix_key")
            if not 0 < prefix_len <= prompt.size:
                raise ValueError(
                    f"prefix_len {prefix_len} out of range for a "
                    f"{prompt.size}-token prompt")
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
            req = _Request(rid, prompt, int(max_new_tokens),
                           temperature=temperature, top_k=top_k,
                           top_p=top_p, seed=seed)
            req.prefix_key = prefix_key
            req.prefix_len = prefix_len
            if session_id is not None:
                req.session_id = str(session_id)
            if self._journal is not None and _journal_record:
                # write-ahead durable session: journaled BEFORE the request
                # is visible to the engine, so a crash at any later point
                # leaves a reconstructible session; an append failure
                # errors THIS submit instead of admitting an
                # unrecoverable request (restore_session suppresses this —
                # it journals the canonical un-forced session itself)
                self._journal.record_session(
                    req.session_id, prompt.tolist(), {
                        "max_new": int(max_new_tokens),
                        "temperature": float(temperature),
                        "top_k": int(top_k), "top_p": float(top_p),
                        "seed": int(seed), "prefix_key": prefix_key,
                        "prefix_len": prefix_len,
                    }, phash=_prefix_hash(prompt))
            self._waiting.append(req)
        return req

    def result(self, req: _Request, timeout: Optional[float] = None):
        if not req.event.wait(timeout):
            raise TimeoutError(f"request {req.rid} not finished")
        if req.error is not None:
            raise req.error
        return list(req.tokens)

    def session_result(self, req: _Request,
                       timeout: Optional[float] = None) -> List[int]:
        """Full session completion: tokens emitted by previous
        incarnations of a restored session, then this incarnation's
        output. For a never-restored request this equals :meth:`result`."""
        return list(req.pre_emitted) + self.result(req, timeout)

    # ---- session survivability (checkpoint / restore) ----
    def checkpoint_session(self, req: _Request, *,
                           export_kv: bool = True) -> dict:
        """Snapshot a live request into a portable session checkpoint.

        Returns ``{"session": {...}, "kv": blob-or-None}`` in canonical
        session form — the ORIGINAL prompt, the original sampling params,
        and every token emitted across all incarnations — so a checkpoint
        of a restored session round-trips losslessly. ``kv`` carries the
        exported page blob (:meth:`PagedKVPool.export_session`) when the
        request occupies a slot with written pages; it is None for
        waiting/mid-prefill/finished requests (and when ``export_kv`` is
        false), in which case the receiver takes the cold re-prefill path.

        Pending drains are flushed first so the emitted-token view and the
        KV length agree; the compact permutation has already been applied
        to ``_slot_pages`` by ``_maybe_compact``, so the page list handed
        to the pool is in logical order."""
        with self._engine_lock:
            while self._pending:
                self._drain_one()
            n_pre = len(req.pre_emitted)
            orig_prompt = req.prompt[:req.prompt.size - n_pre]
            sess = {
                "id": req.session_id,
                "prompt": [int(t) for t in orig_prompt],
                "params": {
                    "max_new": int(req.max_new) + n_pre,
                    "temperature": req.temperature, "top_k": req.top_k,
                    "top_p": req.top_p, "seed": req.seed,
                },
                "phash": _prefix_hash(orig_prompt),
                "emitted": list(req.pre_emitted) + list(req.tokens),
            }
            kv = None
            if export_kv and not req.done and not self._spec:
                slot = next((i for i in range(self._S)
                             if self._slot_req[i] is req), None)
                if (slot is not None and slot not in self._chunking
                        and req.tokens and self._slot_pages[slot]):
                    # positions written so far: the full (possibly forced)
                    # prompt plus every emitted token EXCEPT the last —
                    # the last emission is the next tick's input and has
                    # no KV entry yet
                    written = req.prompt.size + len(req.tokens) - 1
                    n_live = self._kv.pages_per_slot(written)
                    kv = self._kv.export_session(
                        self._slot_pages[slot][:n_live], length=written)
            return {"session": sess, "kv": kv}

    def restore_session(self, sess: dict,
                        kv_blob: Optional[dict] = None) -> _Request:
        """Rebuild a journaled/checkpointed session on THIS engine.

        Cold path (``kv_blob is None``): re-prefill the original prompt
        plus every previously emitted token as a forced prefix and decode
        the remainder — deterministic for greedy (teacher-forcing the
        emitted tokens reproduces the uninterrupted run's schedule
        exactly; sampled sessions also continue on-schedule because the
        PRNG folds the request seed at absolute emit positions).

        Warm path: adopt the exported KV pages into this engine's pool and
        occupy a slot directly — ZERO re-prefilled tokens; the next tick
        feeds the last emitted token at its original position.

        Either way the returned request generates only the REMAINDER;
        read the full completion with :meth:`session_result`. A session
        whose budget is already spent (or that already emitted eos)
        returns a completed request immediately."""
        prompt = np.asarray(sess.get("prompt", ()), np.int32).reshape(-1)
        params = dict(sess.get("params", {}))
        emitted = [int(t) for t in sess.get("emitted", ())]
        sid = sess.get("id")
        max_new = int(params.get("max_new", 32))
        temperature = float(params.get("temperature", 0.0))
        top_k = int(params.get("top_k", 0))
        top_p = float(params.get("top_p", 1.0))
        seed = int(params.get("seed", 0))
        remaining = max_new - len(emitted)
        finished = (remaining <= 0
                    or (self._eos is not None and self._eos in emitted))
        if finished:
            with self._lock:
                rid = self._next_rid
                self._next_rid += 1
            req = _Request(rid, prompt, max(1, max_new),
                           temperature=temperature, top_k=top_k,
                           top_p=top_p, seed=seed)
            if sid is not None:
                req.session_id = str(sid)
            req.pre_emitted = emitted
            req.done = True
            req.journaled = -1
            req.finished_at = time.perf_counter()
            req.event.set()
            return req
        forced = (np.concatenate([prompt,
                                  np.asarray(emitted, np.int32)])
                  if emitted else prompt)
        if sid is None:
            with self._lock:
                sid = f"sess-{self._next_rid}"
        sid = str(sid)
        if self._journal is not None:
            # re-journal the CANONICAL session on this engine (original
            # prompt + merged tail) BEFORE the request becomes visible —
            # the engine thread's first tail record must find its sess
            # record — so a second failover replays from here without
            # accumulating forced prefixes
            self._journal.record_session(
                sid, prompt.tolist(), {
                    "max_new": max_new, "temperature": temperature,
                    "top_k": top_k, "top_p": top_p, "seed": seed,
                    "prefix_key": None, "prefix_len": None,
                }, phash=_prefix_hash(prompt))
            if emitted:
                self._journal.record_session_tokens(sid, emitted)
        if kv_blob is None:
            # cold: the forced prompt re-prefills through the normal
            # admission path (grouped/chunked prefill, page budgeting)
            req = self.submit(forced, max_new_tokens=remaining,
                              temperature=temperature, top_k=top_k,
                              top_p=top_p, seed=seed,
                              session_id=sid, _journal_record=False)
            req.pre_emitted = emitted
            return req
        return self._adopt_warm(sess, kv_blob, forced, remaining,
                                temperature, top_k, top_p, seed, sid,
                                emitted)

    def _adopt_warm(self, sess, kv_blob, forced, remaining, temperature,
                    top_k, top_p, seed, sid, emitted) -> _Request:
        """Warm-path slot occupation for :meth:`restore_session`."""
        if self._spec:
            raise ValueError("warm adopt is not supported on speculative "
                             "engines (the draft cache is not exported); "
                             "restore cold instead")
        if not emitted:
            raise ValueError("warm adopt needs >= 1 emitted token (the "
                             "next tick's input); restore cold instead")
        written = int(kv_blob.get("length", -1))
        if written != forced.size - 1:
            raise ValueError(
                f"kv blob holds {written} positions; session expects "
                f"{forced.size - 1} (prompt+emitted minus the pending "
                f"last token)")
        if forced.size + remaining > self._L:
            raise ValueError(
                f"session needs {forced.size + remaining} positions; "
                f"this engine's max_len is {self._L}")
        with self._engine_lock:
            slot = next((i for i in range(self._S)
                         if self._slot_req[i] is None
                         and i not in self._chunking), None)
            if slot is None:
                raise PoolExhausted("no free slot to adopt session into")
            adopted = self._kv.adopt_session(kv_blob)
            n_total = self._kv.pages_per_slot(
                self._need(forced.size, remaining))
            try:
                extra = (self._kv.alloc(n_total - len(adopted))
                         if n_total > len(adopted) else [])
            except PoolExhausted:
                self._kv.free(adopted)
                raise
            with self._lock:
                rid = self._next_rid
                self._next_rid += 1
            req = _Request(rid, forced, remaining,
                           temperature=temperature, top_k=top_k,
                           top_p=top_p, seed=seed)
            if sid is not None:
                req.session_id = str(sid)
            req.pre_emitted = list(emitted)
            self._slot_req[slot] = req
            self._slot_pages[slot] = adopted + extra
            self._set_bt_row(slot, adopted + extra)
            # device state: the last emitted token is the next input, at
            # the position it would occupy in the uninterrupted run; the
            # base PRNG key is a pure function of the seed and folds at
            # absolute positions, so sampling continues on-schedule too
            self._tok = self._tok.at[slot].set(int(forced[-1]))
            self._pos = self._pos.at[slot].set(written)
            self._active = self._active.at[slot].set(True)
            self._remaining = self._remaining.at[slot].set(remaining)
            self._temp = self._temp.at[slot].set(temperature)
            self._topk = self._topk.at[slot].set(top_k)
            self._topp = self._topp.at[slot].set(top_p)
            self._key = self._key.at[slot].set(
                jax.random.PRNGKey(seed).astype(jnp.uint32))
            self.stats["sessions_adopted"] = \
                self.stats.get("sessions_adopted", 0) + 1
            _tracing.add_event("session_adopt", slot=slot,
                               pages=len(adopted), extra=len(extra),
                               written=written)
        return req

    # ---- engine ----
    def _admit(self):
        """Move waiting requests into free slots.

        Plain requests admitted in the same tick BATCH their prefill:
        same-bucket prompts run as one multi-row ``prefill_cache`` call
        instead of one call per request — outputs are unchanged because
        prefill rows are independent. The row dimension pads to a power
        of two so a pool of S slots compiles at most log2(S)+1 prefill
        programs per prompt bucket (a per-group-size shape would compile
        on every distinct burst size). Prefix-cache requests keep the
        individual path (their suffix windows and store bookkeeping are
        per-request)."""
        while True:
            # staged units first (their prefill already ran in the
            # background): insertion is one dispatch + one queued fetch
            staged_any = False
            while self._staged:
                with self._lock:
                    free = [i for i in range(self._S)
                            if self._slot_req[i] is None]
                    if not free:
                        break
                    unit = self._staged[0]
                    reqs, logits, rows, off = unit
                    m = min(len(free), len(reqs) - off)
                    group = [(free[i], reqs[off + i]) for i in range(m)]
                    for slot, req in group:
                        self._slot_req[slot] = req
                if not self._insert_rows(
                        group, logits[off:off + m],
                        [{kk: c[kk][off:off + m] for kk in ("k", "v")}
                         for c in rows]):
                    # pool exhausted: un-assign, keep the unit parked —
                    # pages free as slots retire, a later tick retries
                    with self._lock:
                        for slot, _ in group:
                            self._slot_req[slot] = None
                    return
                unit[3] += m
                if unit[3] >= len(unit[0]):
                    self._staged.pop(0)
                staged_any = True
            with self._lock:
                free = [i for i in range(self._S)
                        if self._slot_req[i] is None]
                batch = []
                while free and self._waiting:
                    slot = free.pop(0)
                    req = self._waiting.pop(0)
                    self._slot_req[slot] = req
                    batch.append((slot, req))
            if not batch:
                if staged_any:
                    continue  # insertions may have freed slots (max_new=1)
                return
            plain, chunked, prefixed = [], [], []
            for s, r in batch:
                if r.prefix_key is not None:
                    prefixed.append((s, r))
                elif self._needs_chunk(r):
                    chunked.append((s, r))
                else:
                    plain.append((s, r))

            by_bucket: Dict[int, list] = {}
            for s, r in plain:
                by_bucket.setdefault(self._bucket(r.prompt.size),
                                     []).append((s, r))
            # grouped plain prefill, one call per pad bucket. On ANY
            # insertion failure below, the failed request AND every
            # still-uninserted assigned request (later bucket groups,
            # remaining prefixed, all chunked) must go back to the
            # queue together: a request left in _slot_req with no pages
            # counts as decode_live, so the tick would replay its stale
            # device lanes as real tokens until max_new "completes" it.
            groups = list(by_bucket.values())
            for gi, group in enumerate(groups):
                logits, row_cache = self._prefill_group(
                    [r for _, r in group])
                if not self._insert_rows(group, logits, row_cache):
                    self._requeue([p for g in groups[gi:] for p in g]
                                  + prefixed + chunked)
                    return
            for pi, (slot, req) in enumerate(prefixed):
                try:
                    ok = self._admit_prefixed(slot, req)
                except ValueError as e:
                    # request-level validation (e.g. prefix mismatch)
                    # fails ALONE: slot freed, waiter woken with the
                    # error, engine keeps serving (generation.py's
                    # 'malformed field must not poison the batch'
                    # contract). Runtime/device errors are NOT caught —
                    # they propagate to the driver loop's recovery path.
                    req.error = e
                    req.done = True
                    req.finished_at = time.perf_counter()
                    req.event.set()
                    if self._journal is not None and req.journaled >= 0:
                        # a validation-failed request is not recoverable —
                        # retire its journaled session
                        self._journal.record_session_end(req.session_id)
                        req.journaled = -1
                    self._release_locked(slot)
                    continue
                if not ok:
                    self._requeue(prefixed[pi:] + chunked)
                    return
            # long prompts admit into chunked prefill LAST: on page
            # exhaustion everything already admitted above stays admitted
            for i, (slot, req) in enumerate(chunked):
                if not self._begin_chunked(slot, req):
                    self._requeue(chunked[i:])
                    return
            # loop: slots may have freed (eos/max_new on the first token)
            # while waiters remain — constant stack, unlike recursion

    def _prefill_group(self, reqs):
        """ONE batched prefill over same-bucket requests: zero-padded ids,
        power-of-two row pad, pad rows length 1 — THE policy for both
        admitted and staged prefills (the compiled-program-count cap,
        log2(S)+1 per bucket, depends on the two paths staying
        identical). Returns (logits, row_cache); rows past ``len(reqs)``
        are pad garbage."""
        padded = self._bucket(max(r.prompt.size for r in reqs))
        with _prof_span("continuous.prefill", requests=len(reqs),
                        bucket=padded):
            k = 1 << (len(reqs) - 1).bit_length()
            ids = np.zeros((k, padded), np.int32)
            lengths = np.ones(k, np.int32)
            for i, r in enumerate(reqs):
                ids[i, :r.prompt.size] = r.prompt
                lengths[i] = r.prompt.size
            ids_d, lengths_d = jnp.asarray(ids), jnp.asarray(lengths)
            logits, row_cache = self._prefill(self._params, ids_d, lengths_d)
            if self._spec:
                # draft rows ride the same generic row-cache list; insertion
                # zips them against self._cache + self._d_cache
                _, d_rows = self._d_prefill(self._d_params, ids_d, lengths_d)
                row_cache = list(row_cache) + list(d_rows)
            self.stats["prefills"] += 1
            _M_PREFILLS.inc()
        return logits, row_cache

    @staticmethod
    def _padded_rows(n: int) -> int:
        """Device rows a staged n-request unit actually holds (the row
        pad), which is what the ``prefill_ahead`` budget must charge."""
        return 1 << (n - 1).bit_length()

    def _stage_prefills(self):
        """Prefill-ahead: run waiting prompts' prefills while every slot
        is still occupied, parking (logits, KV rows) on device for
        :meth:`_admit` to insert the moment slots retire.

        Takes only the LEADING run of plain same-bucket requests —
        prefix-cache requests keep their per-request suffix path, and a
        bucket change ends the take (cross-bucket grouping would admit a
        later-bucket request before an earlier one across waves; the next
        bucket stages on a later tick, so FIFO holds). The budget charges
        the unit's PADDED row count for its whole lifetime — that is the
        HBM a unit holds until it fully drains. No host sync happens
        here; first tokens are computed and fetched at insertion."""
        with self._lock:
            budget = self._stage_cap - sum(
                self._padded_rows(len(u[0])) for u in self._staged)
            take = []
            bucket = None
            while (self._waiting and self._waiting[0].prefix_key is None
                   and not self._needs_chunk(self._waiting[0])):
                b = self._bucket(self._waiting[0].prompt.size)
                if bucket is None:
                    bucket = b
                elif b != bucket:
                    break
                if self._padded_rows(len(take) + 1) > budget:
                    break
                take.append(self._waiting.pop(0))
        if not take:
            return
        try:
            logits, row_cache = self._prefill_group(take)
        except BaseException:
            # a failed background prefill must not strand its requests in
            # limbo (neither _waiting nor _staged nor a slot —
            # unreachable by cancel_all, waiters hang forever): restore
            # them at the FRONT, order intact, then let the error reach
            # the driver loop's recovery path like any device error
            with self._lock:
                self._waiting[:0] = take
            raise
        self.stats["staged_prefills"] = (
            self.stats.get("staged_prefills", 0) + 1)
        self._staged.append([take, logits, row_cache, 0])

    # ---- page bookkeeping ----
    def _need(self, prompt_len: int, max_new: int) -> int:
        """Cache positions a request must own: prompt + every emittable
        token + the speculative verify window's optimistic tail."""
        return (prompt_len + max_new
                + (self._gamma_max + 1 if self._spec else 0))

    def _upload_bt(self):
        """Re-publish the host block table to device (a few KB — cheap
        relative to any dispatch that reads it)."""
        self._bt = jnp.asarray(self._bt_host)

    def _set_bt_row(self, slot: int, pages, upload: bool = True):
        self._bt_host[slot, :] = 0
        self._bt_host[slot, :len(pages)] = pages
        if upload:
            self._upload_bt()

    def _alloc_with_pressure(self, n: int,
                             protect: Optional[str] = None) -> List[int]:
        """Allocate ``n`` pages, evicting cached prefixes oldest-first
        under pressure (``protect`` shields the key being admitted
        against). Raises :class:`PoolExhausted` once nothing is left to
        evict."""
        while True:
            try:
                # transient exhaustions resolved by the eviction below
                # must not count as alloc_failures — only the terminal
                # one (nothing evictable left) matches that metric's
                # meaning ("failed even after prefix eviction")
                return self._kv.alloc(n, count_failure=False)
            except PoolExhausted:
                victim = next((k for k in self._prefix_store
                               if k != protect), None)
                if victim is None:
                    self._kv.note_alloc_failure()
                    raise
                _, phash, _ = self._prefix_store.pop(victim)
                self._kv.release_prefix(phash)

    def _ensure_pages(self, group):
        """Allocate pages + block-table rows for every slot in ``group``
        that has none yet. Atomic: on exhaustion every allocation made
        here is rolled back before the raise."""
        fresh = []
        try:
            for slot, req in group:
                if self._slot_pages[slot] is not None:
                    continue
                n = self._kv.pages_per_slot(
                    self._need(req.prompt.size, req.max_new))
                fresh.append((slot, self._alloc_with_pressure(n)))
        except PoolExhausted:
            for _, pages in fresh:
                self._kv.free(pages)
            raise
        for slot, pages in fresh:
            self._slot_pages[slot] = pages
            self._set_bt_row(slot, pages, upload=False)
        if fresh:
            self._upload_bt()

    def _requeue(self, group):
        """Back out an admission the pool couldn't hold: slots freed,
        requests back at the FRONT of the queue, order intact."""
        with self._lock:
            self._waiting[:0] = [r for _, r in group]
            for slot, _ in group:
                self._slot_req[slot] = None

    def _insert_rows(self, group, logits, row_cache) -> bool:
        """Slot insertion + first-token emission for an admitted group.

        One device dispatch (``_insert_group_j``) and ONE host fetch per
        POWER-OF-TWO CHUNK of the group — admission used to sync once per
        request (~RTT each over the tunnel), and an arbitrary group size g
        used to compile a fresh insert program per distinct g (a staggered
        second wave admits in sizes 1, 2, 3, 5, ... — each a multi-second
        remote compile that lands in the serving hot path; the r5 campaign
        measured a 23 s first-token stall from exactly this). Chunking to
        descending powers of two caps the program count at log2(S)+1.
        ``logits``/``row_cache`` may carry pad rows past ``len(group)``;
        only the first g rows are used. Returns False (nothing inserted)
        when the page pool cannot hold the group."""
        try:
            self._ensure_pages(group)
        except PoolExhausted:
            return False
        n_t = self._cfg.layers
        off = 0
        while off < len(group):
            size = 1 << ((len(group) - off).bit_length() - 1)
            sl = slice(off, off + size)
            self._insert_chunk_locked(
                group[sl], logits[sl],
                [{kk: c[kk][sl] for kk in ("k", "v")}
                 for c in row_cache[:n_t]],
                [{kk: c[kk][sl] for kk in ("k", "v")}
                 for c in row_cache[n_t:]])
            off += size
        return True

    def _insert_chunk_locked(self, group, logits, rows_t, rows_d):
        """One compiled insert: scatter target rows into the slots' pages
        (``rows_t`` empty for state-only activation — prefix hits and
        chunked prefills already wrote their K/V), write draft rows into
        the draft slot pool, set the per-slot decode state, and queue the
        first tokens on the drain pipeline. Pages must already be
        assigned (:meth:`_ensure_pages`)."""
        g = len(group)
        slots = [s for s, _ in group]
        slots_v = jnp.asarray(slots, jnp.int32)
        lens_v = jnp.asarray([r.prompt.size for _, r in group], jnp.int32)
        rems_v = jnp.asarray([r.max_new - 1 for _, r in group], jnp.int32)
        temps_v = jnp.asarray([r.temperature for _, r in group], jnp.float32)
        topks_v = jnp.asarray([r.top_k for _, r in group], jnp.int32)
        topps_v = jnp.asarray([r.top_p for _, r in group], jnp.float32)
        keys_v = jnp.stack([jax.random.PRNGKey(r.seed)
                            for _, r in group]).astype(jnp.uint32)
        firsts = self._first_tokens(logits[:g], temps_v, topks_v, topps_v,
                                    keys_v, lens_v)
        if rows_t:
            n_pages = -(-rows_t[0]["k"].shape[2] // self._page)
            page_rows = jnp.asarray(self._bt_host[slots, :n_pages],
                                    jnp.int32)
        else:
            page_rows = jnp.zeros((g, 1), jnp.int32)
        if rows_t and self._quant_probe:
            # sampled write-time oracle probe: every quant_probe'th
            # insert roundtrips its (about-to-be-quantized) bf16 rows
            # through quantize/dequantize and reports the relative RMS —
            # the exact kernel-vs-oracle content delta — to the pool
            # gauge and the SLO tracker (one host sync per probe, off
            # the steady-state decode path)
            self._quant_inserts += 1
            if self._quant_inserts % self._quant_probe == 0:
                err, ref = self._quant_probe_j(rows_t[0]["k"])
                rms = float(err) / max(float(ref), 1e-12)
                self._kv.note_quant_error(rms)
                _slo_tracker().note_kv_quant_error(self._slo_model, rms)
        d_cache = self._d_cache if self._spec else []
        sample_state = (self._temp, self._topk, self._topp, self._key)
        (bufs, d_cache, self._tok, self._pos, self._active,
         self._remaining, sample_state) = self._insert_group_j(
            self._kv.buffers, d_cache, slots_v, rows_t, rows_d, page_rows,
            self._tok, self._pos, self._active, self._remaining,
            firsts, lens_v, rems_v, sample_state,
            (temps_v, topks_v, topps_v, keys_v))
        self._kv.buffers = bufs
        if self._spec:
            self._d_cache = d_cache
        self._temp, self._topk, self._topp, self._key = sample_state
        _tracing.add_event(
            "kv_insert", slots=g,
            pages=sum(len(self._slot_pages[s] or ()) for s in slots),
            scattered_rows=g if rows_t else 0)
        # the first tokens ride the drain pipeline as a (1, g) block
        # instead of a synchronous fetch here (~RTT on the admission
        # critical path). Queued BEFORE any subsequent tick block, so
        # drain order replays emission order exactly; an idle engine
        # (nothing else outstanding) drains immediately — same latency
        # as the old synchronous fetch.
        self._pending.append((firsts.reshape(1, -1),
                              {i: (slot, req)
                               for i, (slot, req) in enumerate(group)}))
        if len(self._pending) == 1:
            self._drain_one()

    def _bucket(self, n: int, cap: Optional[int] = None) -> int:
        """THE pad-bucket policy (batched admission, prefix suffix
        windows, and single prefills all share it)."""
        return min(cap if cap is not None else self._L,
                   max(8, bucket_size(n)))

    def _padded_ids(self, tokens: np.ndarray, cap: int) -> np.ndarray:
        """(1, bucketed) right-padded id row."""
        ids = np.zeros((1, self._bucket(tokens.size, cap)), np.int32)
        ids[0, :tokens.size] = tokens
        return ids

    def _admit_prefixed(self, slot: int, req: _Request) -> bool:
        """Admit a ``prefix_key`` request into ``slot``.

        Hit: the first pages of the stored prefix are SHARED physically
        (refcount bump — copy-on-write; only the boundary page the new
        request will write into is copied), private pages cover the rest
        of the request's budget, and one window forward computes the
        suffix. Miss: full prefill into the slot's own pages, then those
        prefix pages register in the pool for the next request to share.
        Raises ValueError on prefix mismatch (fail-alone contract);
        returns False when the pool cannot hold the request."""
        P = req.prompt.size
        hit = self._prefix_store.get(req.prefix_key)
        if hit is not None:
            stored_toks, phash, plen = hit
            # a caller-declared prefix_len shorter than the stored prefix
            # is honored: reuse just that much (the window rewrites the
            # rest), so one stored key serves nested prefixes
            if req.prefix_len is not None:
                plen = min(plen, req.prefix_len)
            if P < plen or not np.array_equal(req.prompt[:plen],
                                              stored_toks[:plen]):
                raise ValueError(
                    f"prefix_key {req.prefix_key!r}: prompt does not "
                    f"start with the stored {plen}-token prefix")
            # whole-prompt hits re-run the last prefix token — one row —
            # to recover its logits
            start = plen if P > plen else plen - 1
            #: pages strictly below the write boundary are shared; the
            #: boundary page itself is COPIED (the suffix window writes
            #: into it, and shared pages are never written)
            s0 = start // self._page
            n_total = self._kv.pages_per_slot(self._need(P, req.max_new))
            try:
                private = self._alloc_with_pressure(
                    n_total - s0, protect=req.prefix_key)
            except PoolExhausted:
                return False
            pages_stored, _ = self._kv.acquire_prefix(phash, s0)
            shared = list(pages_stored[:s0])
            n_copy = -(-plen // self._page) - s0
            if n_copy > 0:
                self._kv.buffers = self._copy_pages_j(
                    self._kv.buffers,
                    jnp.asarray(pages_stored[s0:s0 + n_copy], jnp.int32),
                    jnp.asarray(private[:n_copy], jnp.int32))
            self._slot_pages[slot] = shared + private
            self._set_bt_row(slot, shared + private)
            self.stats["prefix_hits"] += 1
            _M_PREFIX_HITS.inc()
            # LRU promotion: the hit entry becomes the newest
            self._prefix_store[req.prefix_key] = \
                self._prefix_store.pop(req.prefix_key)
            # suffix window over the slot's own pages. Bucketed pad: the
            # garbage K/V a padded lane writes sits at positions the
            # engine overwrites before any mask ever exposes them (or
            # past the allocation, where the block table routes it to
            # the trash page).
            suffix = req.prompt[start:]
            Sn = suffix.size
            ids = self._padded_ids(suffix, self._L - start)
            w_logits, bufs = self._extend_paged(
                self._params, jnp.asarray(ids),
                jnp.asarray([start], jnp.int32),
                self._kv.buffers, self._bt[slot:slot + 1])
            self._kv.buffers = bufs
            self._kv.note_attn_tick(
                self._attn_impl,
                gather_bytes=(self._gather_bytes_extend
                              if self._attn_impl == "gather" else 0))
            self._insert_chunk_locked([(slot, req)], w_logits[:, Sn - 1], [],
                               self._draft_prompt_rows(req))
            return True
        # miss: full prefill into the slot's own pages; cap the pad
        # bucket at max_len (a 40-token prompt in a 48-len cache must
        # not inflate to a 64-wide prefill)
        try:
            self._ensure_pages([(slot, req)])
        except PoolExhausted:
            return False
        ids = self._padded_ids(req.prompt, self._L)
        logits, row_cache = self._prefill(
            self._params, jnp.asarray(ids), jnp.asarray([P], jnp.int32))
        self.stats["prefills"] += 1
        _M_PREFILLS.inc()
        self._insert_chunk_locked(
            [(slot, req)], logits,
            [{kk: c[kk] for kk in ("k", "v")} for c in row_cache],
            self._draft_prompt_rows(req))
        if self._prefix_store_cap > 0:
            # register-on-miss AFTER the insert scattered the rows: the
            # prefix's pages exist only now. The registry increfs them,
            # so they outlive this request's retirement. The slot's own
            # later writes land at positions >= P >= plen — never inside
            # the trusted prefix region (the boundary page's tail may go
            # stale, but every joining request COPIES that page and
            # rewrites the tail before exposing it).
            plen = req.prefix_len if req.prefix_len is not None else P
            phash = _prefix_hash(req.prompt[:plen])
            self._kv.register_prefix(
                phash, self._slot_pages[slot][:-(-plen // self._page)],
                plen)
            if len(self._prefix_store) >= self._prefix_store_cap:
                _, old_hash, _ = self._prefix_store.pop(
                    next(iter(self._prefix_store)))
                self._kv.release_prefix(old_hash)
            self._prefix_store[req.prefix_key] = (
                req.prompt[:plen].copy(), phash, plen)
        return True

    def _draft_prompt_rows(self, req: _Request):
        """Spec mode: the draft's full-prompt prefill rows (the draft
        always re-prefills the whole prompt — a draft is cheap by
        construction). Empty list otherwise — the insert program's
        rows_d slot."""
        if not self._spec:
            return []
        ids = jnp.asarray(self._padded_ids(req.prompt, self._L))
        _, d_rows = self._d_prefill(
            self._d_params, ids,
            jnp.asarray([req.prompt.size], np.int32))
        return [{kk: c[kk] for kk in ("k", "v")} for c in d_rows]

    # ---- chunked prefill ----
    def _chunk_budget(self) -> int:
        return self._tuner.chunk if self._tuner is not None else self._chunk

    def _needs_chunk(self, req: _Request) -> bool:
        """Long plain prompts prefill in budget-bounded chunks instead of
        one monolithic forward (prefix-cache requests keep the suffix
        path — their windows are already short)."""
        return req.prefix_key is None and req.prompt.size > self._chunk_budget()

    def _begin_chunked(self, slot: int, req: _Request) -> bool:
        """Assign pages + block table and park the request in the chunk
        scheduler. The slot is OCCUPIED but device-inactive — decode
        ticks skip it until the final chunk activates it."""
        try:
            self._ensure_pages([(slot, req)])
        except PoolExhausted:
            return False
        self._chunking[slot] = [req, 0]
        return True

    def _advance_chunks(self):
        """Run ONE prefill chunk for the oldest prefilling slot — at most
        one window forward per engine tick, so decode ticks interleave
        with long-prompt prefill and no tick's prefill work exceeds the
        chunk budget. The final chunk computes the first token and
        activates the slot through the state-only insert."""
        if not self._chunking:
            return
        slot = next(iter(self._chunking))
        req, off = self._chunking[slot]
        P = req.prompt.size
        w = min(self._chunk_budget(), P - off)
        ids = self._padded_ids(req.prompt[off:off + w], self._L - off)
        t0 = time.perf_counter()
        with _prof_span("continuous.prefill_chunk", slot=slot,
                        offset=off, tokens=w):
            w_logits, bufs = self._extend_paged(
                self._params, jnp.asarray(ids),
                jnp.asarray([off], jnp.int32),
                self._kv.buffers, self._bt[slot:slot + 1])
        self._kv.buffers = bufs
        _ledger_charge("device_seconds", time.perf_counter() - t0,
                       cls=req.cost_cls, trace_id=req.cost_trace)
        self._kv.note_attn_tick(
            self._attn_impl,
            gather_bytes=(self._gather_bytes_extend
                          if self._attn_impl == "gather" else 0))
        self._kv.note_prefill_chunk(w)
        self._chunk_trace.append(w)
        _tracing.add_event("prefill_chunk", slot=slot, offset=off,
                           tokens=w)
        off += w
        if off < P:
            self._chunking[slot][1] = off
            return
        del self._chunking[slot]
        self.stats["prefills"] += 1
        _M_PREFILLS.inc()
        # first token from the last REAL lane of the final window —
        # logits after consuming prompt position P-1, sampled at emit
        # position P: generate_cached's exact schedule
        self._insert_chunk_locked([(slot, req)], w_logits[:, w - 1], [],
                           self._draft_prompt_rows(req))

    def _note_token(self, req: _Request, tok: int):
        now = time.perf_counter()
        if req.first_token_at is None:
            req.first_token_at = now
        req.tokens.append(tok)
        if ((self._eos is not None and tok == self._eos)
                or len(req.tokens) >= req.max_new):
            req.done = True
            req.finished_at = now
            req.event.set()

    def _release_locked(self, slot: int):
        req = self._slot_req[slot]
        self._slot_req[slot] = None
        self._active = self._active.at[slot].set(False)
        self._chunking.pop(slot, None)
        pages = self._slot_pages[slot]
        if pages:
            # decref (prefix-shared pages survive under their registry
            # refs). The DEVICE block-table row stays stale on purpose:
            # in-flight ticks captured it legitimately, and future ticks
            # see active=False, whose writebacks route to the trash page
            # — a freed page can never be corrupted through a stale row.
            self._kv.free(pages,
                          cost_cls=None if req is None else req.cost_cls,
                          cost_trace=None if req is None else req.cost_trace)
            self._slot_pages[slot] = None
            self._bt_host[slot, :] = 0
            self._maybe_compact()

    def _maybe_compact(self):
        """Defrag on retire: when the pool's live span drifts past the
        threshold, pack live pages dense with ONE device gather and remap
        every host page reference. Safe under pipelining — the gather
        consumes the same buffer refs the in-flight ticks produce, so
        device program order serializes them."""
        if not self._kv.should_compact(self._defrag_thr):
            return
        remap = self._kv.compact()
        if remap is None:
            return
        perm = np.empty_like(remap)
        perm[remap] = np.arange(remap.size)
        self._kv.buffers = self._compact_j(
            self._kv.buffers, jnp.asarray(perm, jnp.int32))
        self._bt_host = remap[self._bt_host].astype(np.int32)
        self._slot_pages = [
            None if p is None else [int(remap[x]) for x in p]
            for p in self._slot_pages]
        self._upload_bt()
        _tracing.add_event("kv_compact",
                           pages_in_use=self._kv.pages_in_use)

    def step(self) -> int:
        """One engine tick; returns the number of live slots stepped.
        Serialized against :meth:`cancel_all` (the only other slot-table
        mutator callable from another thread)."""
        with self._engine_lock:
            return self._step_locked()

    def _step_locked(self) -> int:
        injector = _get_injector()
        if injector.enabled:
            injector.fire("device_run")
        # adaptive drain under saturation: when requests are queued and
        # every slot is occupied, the only way a slot frees is through a
        # drained block's retirement — running `depth` ahead would keep
        # finished slots occupied k·depth more steps and starve admission
        # (the r5 sweep's depth-is-monotone-harmful-at-k=8 mechanism).
        # Drain the MINIMUM outstanding blocks needed to free a slot; an
        # unsaturated pool keeps full pipelining.
        with self._lock:
            # staged units are backlog too: once the whole queue is
            # staged, _waiting is empty but retiring slots still need the
            # eager drain to admit the parked replacements promptly
            backlog = bool(self._waiting or self._staged)
        if backlog:
            while (self._pending
                   and all(self._slot_req[i] is not None
                           for i in range(self._S))
                   and self._retirement_in_flight()):
                self._drain_one()
        self._admit()
        # one prefill chunk per tick, interleaved with the decode below —
        # this IS the chunked-prefill scheduler: long prompts never run
        # more than chunk-budget prefill work in any one tick
        with _watch("decoder_prefill"):
            self._advance_chunks()
        live = [i for i in range(self._S) if self._slot_req[i] is not None]
        _M_LIVE_SLOTS.set(len(live))
        if not live:
            # nothing host-side to step — but outstanding blocks may still
            # hold tokens (and retire slots whose waiters are blocked)
            if self._pending:
                self._drain_one()
                return 1
            return 0
        # slots mid-chunked-prefill are occupied but device-INACTIVE:
        # they must stay out of the tick snapshot (their device lanes
        # would replay tok=0 repeats as real tokens) and out of the
        # temperature checks
        decode_live = [i for i in live if i not in self._chunking]
        if self._tuner is not None:
            self._tuner.observe(
                len(live), self._S,
                self.stats.get("spec_emitted") if self._spec else None,
                self.stats.get("spec_round_slots") if self._spec else None)
        if not decode_live:
            # everything live is still prefilling — the chunk above was
            # this tick's work
            while len(self._pending) > self._depth_now():
                self._drain_one()
            return len(live)
        tick_t0 = time.perf_counter()
        if self._spec:
            gamma_now = (self._tuner.gamma if self._tuner is not None
                         else self._gamma)
            if any(self._slot_req[i].temperature > 0.0
                   for i in decode_live):
                warps = any(self._slot_req[i].temperature > 0.0
                            and (self._slot_req[i].top_k > 0
                                 or self._slot_req[i].top_p < 1.0)
                            for i in decode_live)
                tick = functools.partial(
                    self._spec_tick_for("warped" if warps else "sampled",
                                        gamma_now),
                    temp=self._temp, key=self._key,
                    topk=self._topk, topp=self._topp)
            else:
                tick = self._spec_tick_for("greedy", gamma_now)
            with _watch("decoder_decode"):
                (self._tok, self._pos, self._active, bufs,
                 self._d_cache, self._remaining, toks) = tick(
                    self._params, self._d_params, self._tok, self._pos,
                    self._active, self._kv.buffers, self._bt, self._d_cache,
                    self._remaining)
            self._kv.buffers = bufs
            # round-slot accounting happens at DRAIN time (_drain_one),
            # from the same block that feeds spec_emitted: counting
            # dispatched slots here would include lanes already retired
            # on device, skewing the autotuner's acceptance estimate
            # low for the whole pipeline_depth window
        elif any(self._slot_req[i].temperature > 0.0 for i in decode_live):
            with _watch("decoder_decode"):
                (self._tok, self._pos, self._active, bufs,
                 self._remaining, toks) = self._tick_sampled(
                    self._params, self._tok, self._pos, self._active,
                    self._kv.buffers, self._bt, self._remaining,
                    self._temp, self._topk, self._topp, self._key)
            self._kv.buffers = bufs
        else:
            with _watch("decoder_decode"):
                (self._tok, self._pos, self._active, bufs,
                 self._remaining, toks) = self._tick(
                    self._params, self._tok, self._pos, self._active,
                    self._kv.buffers, self._bt, self._remaining)
            self._kv.buffers = bufs
        # one dispatch covers every live decode slot: apportion its wall
        # time equally across the requests that rode it
        _get_ledger().charge_shares(
            "device_seconds", time.perf_counter() - tick_t0,
            [(self._slot_req[i].cost_cls, self._slot_req[i].cost_trace, 1.0)
             for i in decode_live])
        # per-dispatch attention accounting: k paged calls rode this
        # dispatch; only the gather impl moves materialization bytes
        self._kv.note_attn_tick(
            self._attn_impl, calls=self._k,
            gather_bytes=(self._k * self._gather_bytes_tick
                          if self._attn_impl == "gather" else 0))
        # snapshot slot→REQUEST (not indices): by the time this block is
        # drained, a slot may have been freed and re-admitted; tokens must
        # go to the request that occupied the slot at DISPATCH time (its
        # done guard discards the inactive-slot repeats)
        self._pending.append((toks, {i: (i, self._slot_req[i])
                                     for i in decode_live}))
        # prefill-ahead: with the decode block dispatched (device busy for
        # k steps), background-prefill waiting prompts into the stage
        if self._stage_cap:
            self._stage_prefills()
        # the ONLY host↔device sync on the decode path: fetch the oldest
        # block once `depth` newer dispatches are already queued on device
        while len(self._pending) > self._depth_now():
            self._drain_one()
        return len(live)

    def _depth_now(self) -> int:
        """The live pipeline-depth bound: the autotuner's pick when it is
        running (it follows pool occupancy), else the constructor's."""
        if self._tuner is not None and self._tuner.depth is not None:
            return self._tuner.depth
        return self._depth

    def _retirement_in_flight(self) -> bool:
        """True iff some occupied slot's request could finish inside the
        outstanding blocks (host-visible tokens plus k per in-flight
        block) — draining when nothing can retire would serialize host
        and device for the whole saturated mid-generation window. With
        eos enabled any block may end a request early, so be
        conservative and allow the drain."""
        if self._eos is not None:
            return True
        horizon = self._max_per_dispatch * len(self._pending)
        return any(req is not None
                   and req.max_new - len(req.tokens) <= horizon
                   for req in self._slot_req)

    def _drain_one(self):
        """Fetch + process the oldest outstanding (k, S) token block.
        Device retirement mirrors ``_note_token`` exactly, so a slot emits
        at scan step s iff its request is not yet done host-side when s is
        replayed in order — no device mask needed."""
        toks_dev, snapshot = self._pending.pop(0)
        # the np.asarray is the decode path's only host↔device sync — the
        # exact line a wedged device parks forever, so the watchdog covers it
        drain_t0 = time.perf_counter()
        with _M_DRAIN_SECONDS.time(), _prof_span("continuous.drain"), \
                _watch("decoder_drain"):
            toks = np.asarray(toks_dev)
        _get_ledger().charge_shares(
            "device_seconds", time.perf_counter() - drain_t0,
            [(req.cost_cls, req.cost_trace, 1.0)
             for _, (_, req) in snapshot.items()])
        if self._spec and toks.shape[0] > 1:
            # spec blocks mark unemitted lanes -1. Both acceptance
            # counters come from THIS block so they cover the same
            # window: emissions are the non-negative lanes, and a
            # (round, slot) pair counts as a round-slot iff the slot
            # was still live in that round — a live round always emits
            # >= 1 token (accepted prefix + final), a retired one emits
            # none. The block is k_steps round groups of gamma+1 lanes.
            lanes = toks.shape[0] // self._k
            live_pairs = (toks.reshape(self._k, lanes, -1) >= 0).any(1)
            self.stats["spec_emitted"] = (
                self.stats.get("spec_emitted", 0)
                + int((toks >= 0).sum()))
            self.stats["spec_round_slots"] = (
                self.stats.get("spec_round_slots", 0)
                + int(live_pairs.sum()))
        for s in range(toks.shape[0]):
            for col, (_, req) in snapshot.items():
                if req.done:
                    continue
                tk = int(toks[s, col])
                if tk < 0:
                    continue        # spec lane beyond the accepted count
                self._note_token(req, tk)
        if self._journal is not None:
            # one tail record per session per drain tick (batched: a k-step
            # block journals k tokens in one line); completion closes the
            # session so compaction can drop it
            seen = set()
            for _, (_, req) in snapshot.items():
                if id(req) in seen or req.journaled < 0:
                    continue        # -1 = session already closed
                seen.add(id(req))
                new = req.tokens[req.journaled:]
                if new:
                    self._journal.record_session_tokens(req.session_id, new)
                    req.journaled = len(req.tokens)
                if req.done:
                    self._journal.record_session_end(req.session_id)
                    req.journaled = -1
        for _, (slot, req) in snapshot.items():
            if req.done and self._slot_req[slot] is req:
                self._release_locked(slot)

    def flush(self):
        """Drain every outstanding dispatch (bounded: the pending queue
        only shrinks here). Public so owners handing out tickets can
        guarantee all tokens emitted so far are visible."""
        with self._engine_lock:
            while self._pending:
                self._drain_one()

    def cancel_all(self):
        """Fail every waiting and in-flight request (device-error recovery:
        the owner calls this when :meth:`step` raises persistently, so the
        slot pool can't stay occupied by requests nothing will ever
        retire). Returns the cancelled requests; their ``tokens`` hold
        whatever was emitted before the cancel and ``done`` is set.

        Rebuilds EVERY device-state buffer, not just the active mask: with
        donation on, a tick that raised after dispatch leaves _tok/_pos/
        _cache (and the sampling vectors) referencing donated buffers XLA
        has already deleted — reusing any of them would fail every
        subsequent tick forever. All slots are being freed anyway, so
        fresh zeros are exactly the post-cancel state."""
        # taken by a non-driver thread while serve_forever is mid-step:
        # without this lock the slot sweep races step()'s retire loop
        with self._engine_lock:
            with self._lock:
                waiting, self._waiting = self._waiting, []
            cancelled = list(waiting)
            # staged requests left _waiting but never reached a slot;
            # their parked device buffers are dropped with the units
            for unit in self._staged:
                cancelled.extend(unit[0][unit[3]:])
            self._staged.clear()
            # outstanding blocks may reference donated/deleted buffers
            # after a failed tick — drop them; cancel semantics already
            # promise only "whatever was emitted before the cancel"
            self._pending.clear()
            for i in range(self._S):
                req = self._slot_req[i]
                if req is not None:
                    self._slot_req[i] = None
                    cancelled.append(req)
            self._reset_device_state()
        now = time.perf_counter()
        for req in cancelled:
            req.done = True
            req.finished_at = now
            req.event.set()
        return cancelled

    def serve_forever(self, idle_sleep: float = 0.002,
                      max_failures: int = 3,
                      failure_backoff: float = 0.05):
        """Engine loop with crash containment: a step() error is counted
        and backed off (exponentially, capped at 1s); after
        ``max_failures`` consecutive errors the decoder cancels all
        in-flight requests (their waiters unblock with whatever tokens
        were emitted) and keeps serving rather than dying silently with
        every waiter parked forever."""
        failures = 0
        while not self._stop.is_set():
            try:
                stepped = self.step()
            except Exception as exc:
                failures += 1
                _log_event("continuous_step_failed", failures=failures,
                           error=repr(exc))
                if failures >= max_failures:
                    try:
                        self.cancel_all()
                    except Exception as cancel_exc:
                        _log_event("continuous_cancel_failed",
                                   error=repr(cancel_exc))
                    failures = 0
                self._stop.wait(min(failure_backoff * (2 ** failures), 1.0))
                continue
            failures = 0
            if stepped == 0:
                self._stop.wait(idle_sleep)

    def start(self) -> threading.Thread:
        # the decoder thread starts with an empty context — propagate()
        # carries whatever tracer/trace is active at start() into it, so
        # prefill/drain spans stay attributable
        t = threading.Thread(target=_tracing.propagate(self.serve_forever),
                             daemon=True, name="continuous-decoder")
        t.start()
        return t

    def stop(self):
        self._stop.set()
