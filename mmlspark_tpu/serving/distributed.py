"""Distributed serving: driver registry + cross-worker routing/forwarding.

Parity surface (the round-1 gap): the reference's multi-worker continuous
serving — per-executor ``WorkerServer``s register with a driver rendezvous
service (``DriverServiceUtils.createDriverService``,
``HTTPSourceV2.scala:134-195``), the driver keeps a routing table of live
workers (``:689``), failed/restarted readers re-register under the same id
and rehydrate their unanswered requests (``registerPartition``
``:489-506``), replies are routed to the worker holding the client
connection (``HTTPSourceStateHolder.getServer(machineIp).replyTo``,
``:536-554``), and an internal load balancer forwards requests between
servers (``:679-687``).

TPU-first shape: the engine (the DataFrame pipeline loop) polls *all* local
workers; replies travel back by worker id — over HTTP when the owning worker
is remote, in-process otherwise. Everything is testable with N workers in
one process, exactly how the reference tests distributed behavior in
local-mode Spark (SURVEY §4).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from ..io.http.schema import (EntityData, HeaderData, HTTPRequestData,
                              HTTPResponseData, StatusLineData)
from ..observability import counter as _metric_counter
from ..observability import log_event as _log_event
from ..observability import tracing as _tracing
from ..observability import (ClusterAggregator, ClusterSampler,
                             snapshot_interval, worker_snapshot)
from ..reliability import (DEADLINE_HEADER, BreakerOpen, CircuitBreaker,
                           Deadline, DeadlineExceeded, RetryPolicy,
                           breaker_for, get_injector)
from ..reliability.lock_sanitizer import new_lock
from .admission import ConsistentHashRing
from .kv_pool import AFFINITY_HEADER
from .registry import get_registry as _get_model_registry
from .server import CachedRequest, Overloaded, WorkerServer

__all__ = ["DriverRegistry", "DistributedWorker", "ServingCluster"]

_M_HEARTBEAT_FAILURES = _metric_counter(
    "mmlspark_heartbeat_failures_total",
    "Heartbeat re-register attempts that exhausted their retry budget")


def _giveup(exc: BaseException) -> bool:
    # an HTTPError is a real response (the peer is up — 404 means "already
    # answered", not "try again"); BreakerOpen/DeadlineExceeded are the
    # fail-fast signals retrying would defeat
    return isinstance(exc, (urllib.error.HTTPError, BreakerOpen,
                            DeadlineExceeded))


#: default client policy for cross-process hops: three quick attempts with
#: full jitter — rides out one ECONNREFUSED during a worker restart without
#: stretching a dead-peer verdict past ~1s
_HTTP_RETRY = RetryPolicy(max_attempts=3, base_delay=0.05, max_delay=0.5,
                          retry_on=(OSError,), giveup=_giveup)


def _http_json(url: str, payload: Optional[dict] = None,
               timeout: float = 10.0, *, site: str = "peer_http",
               retry: Optional[RetryPolicy] = None,
               breaker: Optional[CircuitBreaker] = None,
               deadline: Optional[Deadline] = None) -> dict:
    """Retrying, breaker-guarded, deadline-aware JSON-over-HTTP client for
    every cross-process hop. With all guards at their defaults and faults
    disabled the per-attempt work is identical to a plain ``urlopen``."""
    policy = retry if retry is not None else _HTTP_RETRY

    def attempt() -> dict:
        budget = timeout if deadline is None else deadline.cap(timeout)
        if budget <= 0:
            # out of budget is the caller's clock running out, not a peer
            # failure — checked before allow() so it can't strand the
            # half-open probe slot
            raise DeadlineExceeded(url)
        if breaker is not None and not breaker.allow():
            raise BreakerOpen(breaker.peer)
        try:
            injector = get_injector()
            if injector.enabled:
                injector.fire(site)
            data = (json.dumps(payload).encode()
                    if payload is not None else None)
            headers = {"Content-Type": "application/json"}
            if deadline is not None:
                headers[DEADLINE_HEADER] = deadline.header_value()
            req = urllib.request.Request(url, data=data, headers=headers)
            with urllib.request.urlopen(req, timeout=budget) as r:
                out = json.loads(r.read().decode() or "{}")
        except urllib.error.HTTPError:
            # the peer answered — that's a transport success
            if breaker is not None:
                breaker.record_success()
            raise
        except BaseException:
            if breaker is not None:
                breaker.record_failure()
            raise
        if breaker is not None:
            breaker.record_success()
        return out

    return policy.call(attempt, site=site, deadline=deadline)


class _RegistryHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    def _json(self, code: int, payload: dict):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        reg: "DriverRegistry" = self.server.registry  # type: ignore[attr-defined]
        length = int(self.headers.get("Content-Length") or 0)
        payload = json.loads(self.rfile.read(length) or b"{}")
        if self.path == "/register":
            info = reg.register(payload["worker_id"], payload["address"])
            self._json(200, info)
        elif self.path == "/deregister":
            reg.deregister(payload["worker_id"])
            self._json(200, {"ok": True})
        elif self.path == "/heartbeat":
            known = reg.heartbeat(payload["worker_id"],
                                  digest=payload.get("digest"),
                                  telemetry=payload.get("telemetry"))
            self._json(200 if known else 410, {"known": known})
        else:
            self._json(404, {"error": f"no route {self.path}"})

    def do_GET(self):
        reg: "DriverRegistry" = self.server.registry  # type: ignore[attr-defined]
        if self.path == "/routing":
            self._json(200, reg.routing_table())
        elif self.path == "/workers":
            self._json(200, reg.workers())
        elif self.path == "/debug/cluster":
            self._json(200, reg.cluster_view())
        else:
            self._json(404, {"error": f"no route {self.path}"})


class DriverRegistry:
    """Driver-side worker registry + routing table.

    Re-registration with a known ``worker_id`` *replaces* the address and
    bumps the generation — that is the failure-recovery contract
    (``registerPartition`` sees the same epoch and rehydrates,
    ``HTTPSourceV2.scala:489-506``).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 liveness_timeout: float = 30.0):
        self._workers: Dict[str, dict] = {}
        self._lock = new_lock("serving.distributed.DriverRegistry._lock")
        self._generation = 0
        #: cluster-wide metrics federation: merges the counter/histogram/
        #: SLO snapshots workers piggyback on their heartbeats
        self.aggregator = ClusterAggregator()
        #: driver-side time-series plane: cluster series (per-worker
        #: queue depth / in-flight / HBM from digests, merged goodput and
        #: burn rate from the aggregator) accrue at the heartbeat — the
        #: same observation point /debug/cluster serves. Keyed by
        #: worker_id, so a restarted worker continues its series.
        self.timeseries = ClusterSampler()
        self.liveness_timeout = liveness_timeout
        self._httpd = ThreadingHTTPServer((host, port), _RegistryHandler)
        # keep-alive handler threads must not block process exit
        self._httpd.daemon_threads = True
        self._httpd.registry = self  # type: ignore[attr-defined]
        self.host, self.port = host, self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name=f"driver-registry-{self.port}",
                                        daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def _prune_locked(self, now: float) -> None:
        stale = [w for w, i in self._workers.items()
                 if now - i["last_seen"] >= self.liveness_timeout]
        for w in stale:
            del self._workers[w]

    def register(self, worker_id: str, address: str) -> dict:
        now = time.time()
        with self._lock:
            self._prune_locked(now)  # crashed workers never /deregister
            prior = self._workers.get(worker_id)
            self._generation += 1
            self._workers[worker_id] = {"address": address,
                                        "generation": self._generation,
                                        "last_seen": now}
            return {"generation": self._generation,
                    "recovered": prior is not None,
                    "peers": {w: i["address"]
                              for w, i in self._workers.items()}}

    def deregister(self, worker_id: str) -> None:
        with self._lock:
            self._workers.pop(worker_id, None)
            self._generation += 1
        # federation history survives the departure (forget() keeps the
        # accumulated totals — a dead worker's work still happened)
        self.aggregator.forget(worker_id)

    def heartbeat(self, worker_id: str, digest: Optional[dict] = None,
                  telemetry: Optional[dict] = None) -> bool:
        with self._lock:
            info = self._workers.get(worker_id)
            if info is None:
                return False
            info["last_seen"] = time.time()
            if digest is not None:
                info["digest"] = digest
        if telemetry is not None:
            self.aggregator.ingest(worker_id, telemetry)
        # feed the cluster series at the observation point: digest fields
        # directly, goodput/burn from the aggregator's merged totals only
        # when this heartbeat actually carried telemetry (otherwise the
        # delta window would dilute to zero)
        self.timeseries.observe(
            worker_id, digest=digest,
            scorecard=(self.aggregator.scorecard()
                       if telemetry is not None else None))
        return True

    def routing_table(self) -> Dict[str, str]:
        now = time.time()
        with self._lock:
            self._prune_locked(now)
            return {w: i["address"] for w, i in self._workers.items()}

    def workers(self) -> Dict[str, dict]:
        """Per-worker health view: routing info + the latest heartbeat
        digest (queue depth, in-flight, open breakers, stall age)."""
        now = time.time()
        with self._lock:
            self._prune_locked(now)
            return {w: {"address": i["address"],
                        "generation": i["generation"],
                        "last_seen_age": round(now - i["last_seen"], 3),
                        "digest": i.get("digest")}
                    for w, i in self._workers.items()}

    def cluster_view(self) -> dict:
        """The ``GET /debug/cluster`` payload: merged Prometheus text,
        the cluster SLO scorecard, and per-worker health digests."""
        return {"metrics": self.aggregator.render(),
                "scorecard": self.aggregator.scorecard(),
                "timeseries": self.timeseries.snapshot(),
                "workers": self.workers()}

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


class DistributedWorker:
    """A WorkerServer registered with a driver, with cross-worker routing.

    Internal control endpoints (parity: the reference's internal server +
    load balancer, ``HTTPSourceV2.scala:664-697``):

    * ``/_reply`` — accept a routed reply for a request parked *here*
    * ``/_forward`` — accept a forwarded public request (served locally even
      when this worker is in forwarding mode, to prevent loops)
    """

    def __init__(self, driver_url: str, worker_id: str,
                 host: str = "127.0.0.1", port: int = 0,
                 reply_timeout: float = 60.0,
                 heartbeat_interval: float = 10.0,
                 advertise_host: str = "",
                 max_queue: int = 10_000):
        self.driver_url = driver_url
        self.worker_id = worker_id
        self.max_queue = int(max_queue)
        self.server = WorkerServer(host=host, port=port,
                                   reply_timeout=reply_timeout,
                                   max_queue=self.max_queue)
        self.server.control_routes["/_reply"] = self._handle_remote_reply
        self.has_engine = True
        self._peers: Dict[str, str] = {}
        self._rr = 0
        #: prefix-affine placement: requests carrying a KV-prefix key
        #: (X-Mmlspark-Prefix) route to the worker whose pool already
        #: holds those pages; rebuilt on every peer-table change
        self._ring = ConsistentHashRing()
        #: worker id → forwards currently in flight (bounded-load input)
        self._fwd_inflight: Dict[str, int] = {}
        self._lock = new_lock("serving.distributed.DistributedWorker._lock")
        # the registered address must be PEER-routable: a 0.0.0.0 bind
        # address handed to peers would make them connect to themselves
        # (and /_forward always serves locally, so the wrong worker answers)
        if not advertise_host and host in ("0.0.0.0", "::"):
            import socket as _socket
            advertise_host = _socket.gethostbyname(_socket.gethostname())
        advertised = (f"http://{advertise_host}:{self.server.port}"
                      if advertise_host else self.server.address.rstrip("/"))
        self.advertised_address = advertised.rstrip("/")
        # its own site name: construction-time registration is not a peer
        # hop, and chaos specs targeting peer_http must not be able to kill
        # a worker while it boots
        info = _http_json(driver_url + "/register",
                          {"worker_id": worker_id,
                           "address": self.advertised_address},
                          site="register")
        self.generation = info["generation"]
        self.recovered = info["recovered"]
        self._peers = {w: a for w, a in info["peers"].items()
                       if w != worker_id}
        self._ring.rebuild(self._peers)
        # forwarding entry: serve locally, never re-forward
        self.server.control_routes["/_forward"] = self._handle_forwarded
        # keep last_seen fresh — without this the registry's liveness filter
        # would silently drop every worker after liveness_timeout
        self._hb_stop = threading.Event()
        # federation pacing: 0.0 forces telemetry on the FIRST heartbeat
        self._last_telemetry_t = 0.0
        # re-register retries get their own, more patient budget than the
        # default client policy — losing the registry entry for good is
        # worse than a slightly tardy heartbeat tick
        self._hb_policy = RetryPolicy(max_attempts=4, base_delay=0.1,
                                      max_delay=1.0, retry_on=(OSError,),
                                      giveup=_giveup)
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, args=(heartbeat_interval,),
            name=f"heartbeat-{worker_id}", daemon=True)
        self._hb_thread.start()

    def _heartbeat_loop(self, interval: float) -> None:
        while not self._hb_stop.wait(interval):
            if self.heartbeat():
                continue
            # registry forgot us (pruned while unreachable) → re-register;
            # a permanently-lost worker must be VISIBLE, not silent
            try:
                _http_json(self.driver_url + "/register",
                           {"worker_id": self.worker_id,
                            "address": self.advertised_address},
                           site="heartbeat", retry=self._hb_policy)
            except Exception as exc:
                _M_HEARTBEAT_FAILURES.inc()
                _log_event("heartbeat_reregister_failed",
                           worker_id=self.worker_id, error=repr(exc))

    # -- registry interaction ----------------------------------------------
    def refresh_peers(self) -> Dict[str, str]:
        table = _http_json(self.driver_url + "/routing", site="peer_http")
        with self._lock:
            self._peers = {w: a for w, a in table.items()
                           if w != self.worker_id}
            peers = dict(self._peers)
        # ring membership follows the routing table — restart_worker and
        # deregister both end here (ServingCluster refreshes every peer),
        # so only ~1/n of the prefix keyspace moves per membership change
        if self._ring.rebuild(peers):
            _log_event("ring_rebuilt", worker_id=self.worker_id,
                       nodes=len(peers))
        return peers

    def heartbeat(self) -> bool:
        """One keep-alive tick. Every heartbeat piggybacks the server's
        health digest; a compact metrics+SLO snapshot rides along at the
        federation interval (``MMLSPARK_TPU_FEDERATION_INTERVAL``: 0 =
        every heartbeat, negative = disabled) — the driver merges it into
        the cluster aggregator with counter-reset protection."""
        # canary governance ticks here, off the request path: one rolling
        # window comparison per heartbeat interval (auto-rollback fires
        # even on a worker receiving no canary traffic of its own)
        try:
            _get_model_registry().check_canaries()
        except Exception as exc:
            _log_event("canary_check_failed", worker_id=self.worker_id,
                       error=repr(exc))
        payload = {"worker_id": self.worker_id,
                   "digest": self.server.health_digest()}
        interval = snapshot_interval()
        now = time.monotonic()
        send_telemetry = (interval >= 0
                          and (interval == 0
                               or now - self._last_telemetry_t >= interval))
        if send_telemetry:
            payload["telemetry"] = worker_snapshot()
        try:
            out = _http_json(self.driver_url + "/heartbeat", payload,
                             site="heartbeat").get("known", False)
        except Exception:
            return False
        if send_telemetry and out:
            self._last_telemetry_t = now
        return out

    # -- engine surface ------------------------------------------------------
    def get_batch(self, max_rows: int, timeout: float = 0.1
                  ) -> List[Tuple[str, CachedRequest]]:
        return [(self.worker_id, c)
                for c in self.server.get_batch(max_rows, timeout)]

    # -- reply routing -------------------------------------------------------
    def reply(self, owner_id: str, request_id: str,
              response: HTTPResponseData) -> bool:
        """Reply to a request parked on ``owner_id`` — locally or over HTTP
        (parity: ``HTTPSourceStateHolder.getServer(ip).replyTo``)."""
        if owner_id == self.worker_id:
            return self.server.reply(request_id, response)
        addr = self._peers.get(owner_id)
        if addr is None:
            try:
                self.refresh_peers()
            except Exception:
                return False
            addr = self._peers.get(owner_id)
            if addr is None:
                return False
        try:
            out = _http_json(addr + "/_reply",
                             {"request_id": request_id,
                              "response": response.to_dict()},
                             breaker=breaker_for(addr))
        except Exception:
            # same contract as the local branch: an already-answered /
            # timed-out / unreachable target is False, never an exception
            return False
        return bool(out.get("ok"))

    def _handle_remote_reply(self, req: HTTPRequestData) -> HTTPResponseData:
        payload = json.loads(req.entity.content if req.entity else b"{}")
        # server.reply is where the request's root span closes (exactly
        # once, on THIS owning worker) and its counters tick — the hop
        # itself only logs, so forwarded replies aren't double-billed
        ok = self.server.reply(payload["request_id"],
                               HTTPResponseData.from_dict(payload["response"]))
        _log_event("remote_reply", worker_id=self.worker_id,
                   request_id=payload.get("request_id"), ok=ok)
        return HTTPResponseData(
            entity=EntityData.from_string(json.dumps({"ok": ok})),
            status_line=StatusLineData(status_code=200 if ok else 404))

    # -- request forwarding (load balancing) ---------------------------------
    _FWD_PREFIX = "/_forward"
    _FWD_HDR = "X-Mmlspark-Original-Method"

    def _handle_forwarded(self, req: HTTPRequestData) -> HTTPResponseData:
        # restore the client's original path/query and method before parking
        if req.url.startswith(self._FWD_PREFIX):
            req.url = req.url[len(self._FWD_PREFIX):] or "/"
        for h in req.headers:
            if h.name == self._FWD_HDR:
                req.method = h.value
        req.headers = [h for h in req.headers if h.name != self._FWD_HDR]
        try:
            cached = self.server._enqueue(req)
        except Overloaded as exc:
            return HTTPResponseData(
                headers=[HeaderData("Retry-After", f"{exc.retry_after:g}")],
                status_line=StatusLineData(status_code=429,
                                           reason_phrase="overloaded"))
        resp = cached.wait(self.server.wait_budget(cached))
        if resp is None:
            return HTTPResponseData(
                status_line=StatusLineData(status_code=504,
                                           reason_phrase="forwarded timeout"))
        return resp

    def enable_forwarding(self) -> None:
        """Engine detached: forward public requests round-robin to peers
        instead of parking them (parity: load balancer ``:679-687``)."""
        self.has_engine = False
        self.server.control_routes["/"] = self._forward_out

    def disable_forwarding(self) -> None:
        self.has_engine = True
        self.server.control_routes.pop("/", None)

    def _note_forward(self, worker_id: str, delta: int) -> None:
        with self._lock:
            n = self._fwd_inflight.get(worker_id, 0) + delta
            if n > 0:
                self._fwd_inflight[worker_id] = n
            else:
                self._fwd_inflight.pop(worker_id, None)

    def _forward_candidates(self, req: HTTPRequestData
                            ) -> List[Tuple[str, str]]:
        """Peer try-order for one forwarded request as ``(worker_id,
        address)`` pairs. Requests carrying a KV-prefix affinity key
        (``X-Mmlspark-Prefix``, the hex of ``PagedKVPool.prefix_hash``)
        go ring-first: the owning worker's pool already holds their
        shared-prefix pages, with bounded-load fallback to the next ring
        position when the owner is saturated. Unkeyed requests keep the
        round-robin rotation."""
        affinity = None
        for h in req.headers:
            if h.name.lower() == AFFINITY_HEADER.lower():
                affinity = h.value.strip() or None
        with self._lock:
            peer_map = dict(self._peers)
            start = self._rr
            self._rr += 1
            load = dict(self._fwd_inflight)
        if not peer_map:
            return []
        if affinity is not None and len(self._ring):
            first = self._ring.route(affinity, load=load)
            order = [w for w in self._ring.preferred(affinity)
                     if w in peer_map]
            if first in peer_map:
                order = [first] + [w for w in order if w != first]
            if order:
                return [(w, peer_map[w]) for w in order]
        items = sorted(peer_map.items())
        return [items[(start + i) % len(items)] for i in range(len(items))]

    def _forward_out(self, req: HTTPRequestData) -> HTTPResponseData:
        candidates = self._forward_candidates(req)
        if not candidates:
            return HTTPResponseData(
                status_line=StatusLineData(status_code=503,
                                           reason_phrase="no peers"))
        # never wait past what the client has left: honor an inbound
        # deadline, else budget this hop with our own reply_timeout
        deadline = None
        for h in req.headers:
            if h.name.lower() == DEADLINE_HEADER.lower():
                deadline = Deadline.from_header(h.value)
        if deadline is None:
            deadline = Deadline.after(self.server.reply_timeout)
        body = req.entity.content if req.entity else None
        # carry the client's path/query, method, and headers across the hop
        base_hdrs = {h.name: h.value for h in req.headers
                     if h.name.lower() not in ("host", "content-length",
                                               "connection")}
        base_hdrs[self._FWD_HDR] = req.method
        injector = get_injector()
        # try each peer at most once, in candidate order, skipping open
        # circuits; 502 only once every peer has been exhausted
        for wid, addr in candidates:
            brk = breaker_for(addr)
            remaining = deadline.remaining()
            if remaining <= 0:
                return HTTPResponseData(
                    status_line=StatusLineData(status_code=504,
                                               reason_phrase="deadline"))
            if not brk.allow():
                continue
            hop_hdrs = dict(base_hdrs)
            hop_hdrs[DEADLINE_HEADER] = deadline.header_value()
            fwd = urllib.request.Request(
                addr + self._FWD_PREFIX + req.url, data=body,
                headers=hop_hdrs, method="POST" if body else "GET")
            self._note_forward(wid, +1)
            try:
                if injector.enabled:
                    injector.fire("peer_http")
                # the peer enforces the deadline (parks at most `remaining`);
                # the socket timeout is only a dead-peer guard, and needs
                # slack so the peer's own 504 arrives instead of racing it
                with urllib.request.urlopen(fwd, timeout=remaining + 1.0) as r:
                    payload = r.read()
                    brk.record_success()
                    return HTTPResponseData(
                        entity=EntityData(content=payload,
                                          content_length=len(payload)),
                        status_line=StatusLineData(status_code=r.status))
            except urllib.error.HTTPError as e:
                # the peer answered (504/429/...): relay it, don't fail over
                payload = e.read()
                brk.record_success()
                return HTTPResponseData(
                    entity=EntityData(content=payload,
                                      content_length=len(payload)),
                    status_line=StatusLineData(status_code=e.code))
            except Exception as exc:
                brk.record_failure()
                _tracing.add_event("forward_failover", peer=addr,
                                   error=type(exc).__name__)
            finally:
                self._note_forward(wid, -1)
        return HTTPResponseData(
            status_line=StatusLineData(status_code=502,
                                       reason_phrase="no reachable peer"))

    def close(self, deregister: bool = True) -> None:
        self._hb_stop.set()
        if deregister:
            try:
                _http_json(self.driver_url + "/deregister",
                           {"worker_id": self.worker_id}, site="register")
            except Exception as exc:
                # best-effort on shutdown (liveness pruning will finish the
                # job), but leave a trace for anyone chasing ghosts
                _log_event("deregister_failed", worker_id=self.worker_id,
                           error=repr(exc))
        self.server.close()
        self._hb_thread.join(timeout=2)


class ServingCluster:
    """N distributed workers + driver registry in one process — the test
    harness shape (reference tests distributed serving in local mode too,
    SURVEY §4). The aggregate ``get_batch``/``reply`` pair is the
    distributed source/sink surface an engine loop drives."""

    def __init__(self, n_workers: int, reply_timeout: float = 60.0,
                 max_queue: int = 10_000):
        self.driver = DriverRegistry()
        self.workers: List[DistributedWorker] = [
            DistributedWorker(self.driver.url, f"worker-{i}",
                              reply_timeout=reply_timeout,
                              max_queue=max_queue)
            for i in range(n_workers)]
        for w in self.workers:
            w.refresh_peers()

    def worker(self, worker_id: str) -> DistributedWorker:
        for w in self.workers:
            if w.worker_id == worker_id:
                return w
        raise KeyError(worker_id)

    def get_batch(self, max_rows: int, timeout: float = 0.05
                  ) -> List[Tuple[str, CachedRequest]]:
        # non-blocking sweep over every worker; one short sleep only if the
        # whole cluster is idle (a per-worker blocking get would add
        # N*timeout dead time to each poll)
        def sweep():
            got: List[Tuple[str, CachedRequest]] = []
            for w in self.workers:
                if not w.has_engine:
                    continue
                got.extend(w.get_batch(max_rows - len(got), timeout=0.0))
                if len(got) >= max_rows:
                    break
            return got

        out = sweep()
        if not out and timeout > 0:
            time.sleep(timeout)
            out = sweep()
        return out

    def reply(self, owner_id: str, request_id: str,
              response: HTTPResponseData) -> bool:
        # any live worker can route the reply; prefer the owner directly
        try:
            return self.worker(owner_id).server.reply(request_id, response)
        except KeyError:
            pass
        # unknown owner (registry drift / restarted elsewhere): route via
        # the first worker whose server is still open — a closed worker
        # can't speak HTTP to the owner anymore
        for w in self.workers:
            if not w.server.closed:
                return w.reply(owner_id, request_id, response)
        return False

    def scorecard(self) -> dict:
        """Cluster SLO scorecard from the driver's federation aggregator,
        with per-worker health digests attached (the in-process twin of
        ``GET /debug/cluster``)."""
        card = dict(self.driver.aggregator.scorecard())
        card["worker_health"] = self.driver.workers()
        return card

    def restart_worker(self, worker_id: str,
                       reply_timeout: Optional[float] = None
                       ) -> DistributedWorker:
        """Chaos/ops helper: kill one worker ungracefully (no deregister —
        a crash doesn't say goodbye) and re-register a replacement under
        the SAME id, exercising the recovery contract."""
        for i, w in enumerate(self.workers):
            if w.worker_id != worker_id:
                continue
            w.close(deregister=False)
            replacement = DistributedWorker(
                self.driver.url, worker_id,
                reply_timeout=(reply_timeout if reply_timeout is not None
                               else w.server.reply_timeout),
                max_queue=w.max_queue)
            self.workers[i] = replacement
            for peer in self.workers:
                try:
                    peer.refresh_peers()
                except Exception as exc:
                    _log_event("refresh_peers_failed",
                               worker_id=peer.worker_id, error=repr(exc))
            return replacement
        raise KeyError(worker_id)

    def close(self) -> None:
        for w in self.workers:
            w.close()
        self.driver.close()
