"""Distributed serving: driver registry + cross-worker routing/forwarding.

Parity surface (the round-1 gap): the reference's multi-worker continuous
serving — per-executor ``WorkerServer``s register with a driver rendezvous
service (``DriverServiceUtils.createDriverService``,
``HTTPSourceV2.scala:134-195``), the driver keeps a routing table of live
workers (``:689``), failed/restarted readers re-register under the same id
and rehydrate their unanswered requests (``registerPartition``
``:489-506``), replies are routed to the worker holding the client
connection (``HTTPSourceStateHolder.getServer(machineIp).replyTo``,
``:536-554``), and an internal load balancer forwards requests between
servers (``:679-687``).

TPU-first shape: the engine (the DataFrame pipeline loop) polls *all* local
workers; replies travel back by worker id — over HTTP when the owning worker
is remote, in-process otherwise. Everything is testable with N workers in
one process, exactly how the reference tests distributed behavior in
local-mode Spark (SURVEY §4).
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

from ..io.http.schema import (EntityData, HeaderData, HTTPRequestData,
                              HTTPResponseData, StatusLineData)
from ..observability import counter as _metric_counter
from ..observability import gauge as _metric_gauge
from ..observability import log_event as _log_event
from ..observability import tracing as _tracing
from ..observability import (ClusterAggregator, ClusterSampler,
                             snapshot_interval, worker_snapshot)
from ..reliability import (DEADLINE_HEADER, BreakerOpen, CircuitBreaker,
                           Deadline, DeadlineExceeded, RetryPolicy,
                           breaker_for, get_injector, start_supervised)
from ..reliability.lock_sanitizer import new_lock
from .admission import ConsistentHashRing
from .journal import ServingJournal
from .kv_pool import AFFINITY_HEADER
from .registry import WORKER_LIVENESS_STATES
from .registry import get_registry as _get_model_registry
from .server import CachedRequest, Overloaded, WorkerServer

__all__ = ["DriverRegistry", "DistributedWorker", "ServingCluster"]

_M_HEARTBEAT_FAILURES = _metric_counter(
    "mmlspark_heartbeat_failures_total",
    "Heartbeat re-register attempts that exhausted their retry budget")

_M_WORKER_LIVENESS = _metric_gauge(
    "mmlspark_worker_liveness",
    "Per-worker liveness state as a one-hot over "
    "alive/suspect/draining/dead (1 for the current state)",
    ("worker", "state"))

_M_DEAD_VERDICTS = _metric_counter(
    "mmlspark_worker_dead_verdicts_total",
    "Workers declared dead by the driver's liveness sweeper")


def _adopt_policy() -> str:
    """``MMLSPARK_TPU_ADOPT_POLICY``: ``warm`` (default) ships exported KV
    page blobs over ``/_adopt`` on graceful drain — zero recompute on the
    receiver; ``cold`` strips the blobs and relies on journal replay /
    re-prefill (deterministic for greedy, cheaper on the wire)."""
    policy = os.environ.get("MMLSPARK_TPU_ADOPT_POLICY", "warm").strip().lower()
    return policy if policy in ("warm", "cold") else "warm"


def _giveup(exc: BaseException) -> bool:
    # an HTTPError is a real response (the peer is up — 404 means "already
    # answered", not "try again"); BreakerOpen/DeadlineExceeded are the
    # fail-fast signals retrying would defeat
    return isinstance(exc, (urllib.error.HTTPError, BreakerOpen,
                            DeadlineExceeded))


#: default client policy for cross-process hops: three quick attempts with
#: full jitter — rides out one ECONNREFUSED during a worker restart without
#: stretching a dead-peer verdict past ~1s
_HTTP_RETRY = RetryPolicy(max_attempts=3, base_delay=0.05, max_delay=0.5,
                          retry_on=(OSError,), giveup=_giveup)


def _http_json(url: str, payload: Optional[dict] = None,
               timeout: float = 10.0, *, site: str = "peer_http",
               retry: Optional[RetryPolicy] = None,
               breaker: Optional[CircuitBreaker] = None,
               deadline: Optional[Deadline] = None) -> dict:
    """Retrying, breaker-guarded, deadline-aware JSON-over-HTTP client for
    every cross-process hop. With all guards at their defaults and faults
    disabled the per-attempt work is identical to a plain ``urlopen``."""
    policy = retry if retry is not None else _HTTP_RETRY

    def attempt() -> dict:
        budget = timeout if deadline is None else deadline.cap(timeout)
        if budget <= 0:
            # out of budget is the caller's clock running out, not a peer
            # failure — checked before allow() so it can't strand the
            # half-open probe slot
            raise DeadlineExceeded(url)
        if breaker is not None and not breaker.allow():
            raise BreakerOpen(breaker.peer)
        try:
            injector = get_injector()
            if injector.enabled:
                injector.fire(site)
            data = (json.dumps(payload).encode()
                    if payload is not None else None)
            headers = {"Content-Type": "application/json"}
            if deadline is not None:
                headers[DEADLINE_HEADER] = deadline.header_value()
            req = urllib.request.Request(url, data=data, headers=headers)
            with urllib.request.urlopen(req, timeout=budget) as r:
                out = json.loads(r.read().decode() or "{}")
        except urllib.error.HTTPError:
            # the peer answered — that's a transport success
            if breaker is not None:
                breaker.record_success()
            raise
        except BaseException:
            if breaker is not None:
                breaker.record_failure()
            raise
        if breaker is not None:
            breaker.record_success()
        return out

    return policy.call(attempt, site=site, deadline=deadline)


class _RegistryHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    def _json(self, code: int, payload: dict):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        reg: "DriverRegistry" = self.server.registry  # type: ignore[attr-defined]
        length = int(self.headers.get("Content-Length") or 0)
        payload = json.loads(self.rfile.read(length) or b"{}")
        if self.path == "/register":
            info = reg.register(payload["worker_id"], payload["address"])
            self._json(200, info)
        elif self.path == "/deregister":
            reg.deregister(payload["worker_id"])
            self._json(200, {"ok": True})
        elif self.path == "/heartbeat":
            known = reg.heartbeat(payload["worker_id"],
                                  digest=payload.get("digest"),
                                  telemetry=payload.get("telemetry"))
            self._json(200 if known else 410, {"known": known})
        else:
            self._json(404, {"error": f"no route {self.path}"})

    def do_GET(self):
        reg: "DriverRegistry" = self.server.registry  # type: ignore[attr-defined]
        if self.path == "/routing":
            self._json(200, reg.routing_table())
        elif self.path == "/workers":
            self._json(200, reg.workers())
        elif self.path == "/debug/cluster":
            self._json(200, reg.cluster_view())
        else:
            self._json(404, {"error": f"no route {self.path}"})


class DriverRegistry:
    """Driver-side worker registry + routing table.

    Re-registration with a known ``worker_id`` *replaces* the address and
    bumps the generation — that is the failure-recovery contract
    (``registerPartition`` sees the same epoch and rehydrates,
    ``HTTPSourceV2.scala:489-506``).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 liveness_timeout: float = 30.0,
                 liveness_interval: Optional[float] = None,
                 sweep_multiplier: Optional[float] = None):
        self._workers: Dict[str, dict] = {}
        self._lock = new_lock("serving.distributed.DriverRegistry._lock")
        self._generation = 0
        #: cluster-wide metrics federation: merges the counter/histogram/
        #: SLO snapshots workers piggyback on their heartbeats
        self.aggregator = ClusterAggregator()
        #: driver-side time-series plane: cluster series (per-worker
        #: queue depth / in-flight / HBM from digests, merged goodput and
        #: burn rate from the aggregator) accrue at the heartbeat — the
        #: same observation point /debug/cluster serves. Keyed by
        #: worker_id, so a restarted worker continues its series.
        self.timeseries = ClusterSampler()
        self.liveness_timeout = liveness_timeout
        # active liveness: a sweeper thread walks the worker table every
        # `liveness_interval` seconds, promoting missed heartbeats past
        # interval x sweep_multiplier to a DEAD verdict (eviction + the
        # on-dead callbacks that reassign journaled sessions). Unset /
        # non-positive keeps the legacy lazy-prune-only behavior.
        if liveness_interval is None:
            raw = os.environ.get("MMLSPARK_TPU_LIVENESS_INTERVAL", "")
            liveness_interval = float(raw) if raw else 0.0
        self.liveness_interval = float(liveness_interval or 0.0)
        if sweep_multiplier is None:
            sweep_multiplier = float(os.environ.get(
                "MMLSPARK_TPU_LIVENESS_SWEEP_MULT", "3.0"))
        self.sweep_multiplier = max(1.0, float(sweep_multiplier))
        #: worker ids mid-graceful-drain: still heartbeating (so not dead),
        #: but excluded from the routing table so no new traffic lands
        self._draining: set = set()
        self._dead_callbacks: List[Callable[[str, dict], None]] = []
        self._sweep_stop = threading.Event()
        self._sweep_thread: Optional[threading.Thread] = None
        self._httpd = ThreadingHTTPServer((host, port), _RegistryHandler)
        # keep-alive handler threads must not block process exit
        self._httpd.daemon_threads = True
        self._httpd.registry = self  # type: ignore[attr-defined]
        self.host, self.port = host, self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name=f"driver-registry-{self.port}",
                                        daemon=True)
        self._thread.start()
        if self.liveness_interval > 0:
            self._sweep_thread = start_supervised(
                self._sweep_once, name=f"liveness-sweeper-{self.port}",
                stop=self._sweep_stop, interval=self.liveness_interval)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def _prune_locked(self, now: float) -> None:
        stale = [w for w, i in self._workers.items()
                 if now - i["last_seen"] >= self.liveness_timeout]
        for w in stale:
            del self._workers[w]
            self._draining.discard(w)

    # -- active liveness ---------------------------------------------------
    def _state_locked(self, worker_id: str, info: dict, now: float) -> str:
        """One of :data:`~.registry.WORKER_LIVENESS_STATES` for a worker
        still present in the table (``dead`` means the sweeper is about to
        evict it — the verdict)."""
        if worker_id in self._draining:
            return "draining"
        if self.liveness_interval <= 0:
            return "alive"
        age = now - info["last_seen"]
        if age < self.liveness_interval:
            return "alive"
        if age < self.liveness_interval * self.sweep_multiplier:
            return "suspect"
        return "dead"

    def _sweep_once(self) -> None:
        """One sweeper tick: refresh the liveness gauge for every worker,
        promote missed heartbeats past interval x multiplier to a dead
        verdict — evict from the table (and hence every routing view), then
        fire the on-dead callbacks outside the lock so they can take HTTP
        hops (session reassignment) without stalling registrations."""
        now = time.time()
        dead: List[Tuple[str, dict]] = []
        with self._lock:
            for w, i in list(self._workers.items()):
                state = self._state_locked(w, i, now)
                for s in WORKER_LIVENESS_STATES:
                    _M_WORKER_LIVENESS.set(1.0 if s == state else 0.0,
                                           worker=w, state=s)
                if state == "dead":
                    dead.append((w, dict(i)))
                    del self._workers[w]
                    self._draining.discard(w)
                    self._generation += 1
        for w, info in dead:
            _M_DEAD_VERDICTS.inc()
            _M_WORKER_LIVENESS.set(1.0, worker=w, state="dead")
            _log_event("worker_dead_verdict", worker_id=w,
                       address=info.get("address"),
                       last_seen_age=round(now - info["last_seen"], 3))
            for fn in list(self._dead_callbacks):
                try:
                    fn(w, info)
                except Exception as exc:
                    _log_event("dead_callback_failed", worker_id=w,
                               error=repr(exc))

    def add_dead_callback(self, fn: Callable[[str, dict], None]) -> None:
        """Register ``fn(worker_id, info)`` to run after a dead verdict
        (post-eviction; ``info`` still carries the last known address and
        digest). ServingCluster hooks session reassignment here."""
        self._dead_callbacks.append(fn)

    def mark_draining(self, worker_id: str) -> bool:
        """Graceful-drain entry: keep the worker registered (it still
        heartbeats and answers its parked requests) but drop it from
        :meth:`routing_table` so peers stop forwarding new work to it."""
        with self._lock:
            if worker_id not in self._workers:
                return False
            self._draining.add(worker_id)
            self._generation += 1
        _log_event("worker_draining", worker_id=worker_id)
        return True

    def register(self, worker_id: str, address: str) -> dict:
        now = time.time()
        with self._lock:
            self._prune_locked(now)  # crashed workers never /deregister
            prior = self._workers.get(worker_id)
            self._generation += 1
            # a re-registration is a fresh incarnation — it starts routable
            self._draining.discard(worker_id)
            self._workers[worker_id] = {"address": address,
                                        "generation": self._generation,
                                        "last_seen": now}
            return {"generation": self._generation,
                    "recovered": prior is not None,
                    "peers": {w: i["address"]
                              for w, i in self._workers.items()}}

    def deregister(self, worker_id: str) -> None:
        with self._lock:
            self._workers.pop(worker_id, None)
            self._draining.discard(worker_id)
            self._generation += 1
        # federation history survives the departure (forget() keeps the
        # accumulated totals — a dead worker's work still happened)
        self.aggregator.forget(worker_id)

    def heartbeat(self, worker_id: str, digest: Optional[dict] = None,
                  telemetry: Optional[dict] = None) -> bool:
        with self._lock:
            info = self._workers.get(worker_id)
            if info is None:
                return False
            info["last_seen"] = time.time()
            if digest is not None:
                info["digest"] = digest
        if telemetry is not None:
            self.aggregator.ingest(worker_id, telemetry)
        # feed the cluster series at the observation point: digest fields
        # directly, goodput/burn from the aggregator's merged totals only
        # when this heartbeat actually carried telemetry (otherwise the
        # delta window would dilute to zero)
        self.timeseries.observe(
            worker_id, digest=digest,
            scorecard=(self.aggregator.scorecard()
                       if telemetry is not None else None))
        return True

    def routing_table(self) -> Dict[str, str]:
        now = time.time()
        with self._lock:
            self._prune_locked(now)
            # draining workers are alive but not routable: peers rebuild
            # their ConsistentHashRing from this table, so exclusion here
            # is what actually moves the prefix keyspace off the worker
            return {w: i["address"] for w, i in self._workers.items()
                    if w not in self._draining}

    def workers(self) -> Dict[str, dict]:
        """Per-worker health view: routing info + liveness state + the
        latest heartbeat digest (queue depth, in-flight, open breakers,
        stall age)."""
        now = time.time()
        with self._lock:
            self._prune_locked(now)
            return {w: {"address": i["address"],
                        "generation": i["generation"],
                        "last_seen_age": round(now - i["last_seen"], 3),
                        "state": self._state_locked(w, i, now),
                        "digest": i.get("digest")}
                    for w, i in self._workers.items()}

    def cluster_view(self) -> dict:
        """The ``GET /debug/cluster`` payload: merged Prometheus text,
        the cluster SLO scorecard, and per-worker health digests."""
        return {"metrics": self.aggregator.render(),
                "scorecard": self.aggregator.scorecard(),
                "timeseries": self.timeseries.snapshot(),
                "workers": self.workers()}

    def close(self) -> None:
        self._sweep_stop.set()
        if self._sweep_thread is not None:
            self._sweep_thread.join(timeout=2)
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


class DistributedWorker:
    """A WorkerServer registered with a driver, with cross-worker routing.

    Internal control endpoints (parity: the reference's internal server +
    load balancer, ``HTTPSourceV2.scala:664-697``):

    * ``/_reply`` — accept a routed reply for a request parked *here*
    * ``/_forward`` — accept a forwarded public request (served locally even
      when this worker is in forwarding mode, to prevent loops)
    """

    def __init__(self, driver_url: str, worker_id: str,
                 host: str = "127.0.0.1", port: int = 0,
                 reply_timeout: float = 60.0,
                 heartbeat_interval: float = 10.0,
                 advertise_host: str = "",
                 max_queue: int = 10_000,
                 journal_path: Optional[str] = None,
                 journal_fsync: bool = False):
        self.driver_url = driver_url
        self.worker_id = worker_id
        self.max_queue = int(max_queue)
        self.journal_path = journal_path
        self.server = WorkerServer(host=host, port=port,
                                   reply_timeout=reply_timeout,
                                   max_queue=self.max_queue,
                                   journal_path=journal_path,
                                   journal_fsync=journal_fsync)
        self.server.control_routes["/_reply"] = self._handle_remote_reply
        self.server.control_routes["/_adopt"] = self._handle_adopt
        #: failover pluggables: ``adopt_handler(payload) -> dict`` overrides
        #: the journal-only default (a decoder harness attaches
        #: ``restore_session`` here); ``session_exporter() -> [entries]``
        #: is what drain_worker calls to checkpoint live sessions
        self.adopt_handler: Optional[Callable[[dict], dict]] = None
        self.session_exporter: Optional[Callable[[], List[dict]]] = None
        #: sessions accepted over ``/_adopt`` (newest last) — the in-memory
        #: twin of the journal record, inspectable by drills and tests
        self.adopted_sessions: List[dict] = []
        self.has_engine = True
        self._peers: Dict[str, str] = {}
        self._rr = 0
        #: prefix-affine placement: requests carrying a KV-prefix key
        #: (X-Mmlspark-Prefix) route to the worker whose pool already
        #: holds those pages; rebuilt on every peer-table change
        self._ring = ConsistentHashRing()
        #: worker id → forwards currently in flight (bounded-load input)
        self._fwd_inflight: Dict[str, int] = {}
        self._lock = new_lock("serving.distributed.DistributedWorker._lock")
        # the registered address must be PEER-routable: a 0.0.0.0 bind
        # address handed to peers would make them connect to themselves
        # (and /_forward always serves locally, so the wrong worker answers)
        if not advertise_host and host in ("0.0.0.0", "::"):
            import socket as _socket
            advertise_host = _socket.gethostbyname(_socket.gethostname())
        advertised = (f"http://{advertise_host}:{self.server.port}"
                      if advertise_host else self.server.address.rstrip("/"))
        self.advertised_address = advertised.rstrip("/")
        # its own site name: construction-time registration is not a peer
        # hop, and chaos specs targeting peer_http must not be able to kill
        # a worker while it boots
        info = _http_json(driver_url + "/register",
                          {"worker_id": worker_id,
                           "address": self.advertised_address},
                          site="register")
        self.generation = info["generation"]
        self.recovered = info["recovered"]
        self._peers = {w: a for w, a in info["peers"].items()
                       if w != worker_id}
        self._ring.rebuild(self._peers)
        # forwarding entry: serve locally, never re-forward
        self.server.control_routes["/_forward"] = self._handle_forwarded
        # keep last_seen fresh — without this the registry's liveness filter
        # would silently drop every worker after liveness_timeout
        self._hb_stop = threading.Event()
        # federation pacing: 0.0 forces telemetry on the FIRST heartbeat
        self._last_telemetry_t = 0.0
        # re-register retries get their own, more patient budget than the
        # default client policy — losing the registry entry for good is
        # worse than a slightly tardy heartbeat tick
        self._hb_policy = RetryPolicy(max_attempts=4, base_delay=0.1,
                                      max_delay=1.0, retry_on=(OSError,),
                                      giveup=_giveup)
        # supervised, not a bare daemon loop: a tick that raises (e.g. a
        # bug in digest collection) is contained and backed off instead of
        # silently killing the heartbeat — which would look exactly like a
        # dead worker to the driver's sweeper
        self._hb_thread = start_supervised(
            self._heartbeat_tick, name=f"heartbeat-{worker_id}",
            stop=self._hb_stop, interval=heartbeat_interval)

    def _heartbeat_tick(self) -> None:
        if self.heartbeat():
            return
        # registry forgot us (pruned while unreachable) → re-register;
        # a permanently-lost worker must be VISIBLE, not silent
        try:
            _http_json(self.driver_url + "/register",
                       {"worker_id": self.worker_id,
                        "address": self.advertised_address},
                       site="heartbeat", retry=self._hb_policy)
        except Exception as exc:
            _M_HEARTBEAT_FAILURES.inc()
            _log_event("heartbeat_reregister_failed",
                       worker_id=self.worker_id, error=repr(exc))

    # -- registry interaction ----------------------------------------------
    def refresh_peers(self) -> Dict[str, str]:
        table = _http_json(self.driver_url + "/routing", site="peer_http")
        with self._lock:
            self._peers = {w: a for w, a in table.items()
                           if w != self.worker_id}
            peers = dict(self._peers)
        # ring membership follows the routing table — restart_worker and
        # deregister both end here (ServingCluster refreshes every peer),
        # so only ~1/n of the prefix keyspace moves per membership change
        if self._ring.rebuild(peers):
            _log_event("ring_rebuilt", worker_id=self.worker_id,
                       nodes=len(peers))
        return peers

    def heartbeat(self) -> bool:
        """One keep-alive tick. Every heartbeat piggybacks the server's
        health digest; a compact metrics+SLO snapshot rides along at the
        federation interval (``MMLSPARK_TPU_FEDERATION_INTERVAL``: 0 =
        every heartbeat, negative = disabled) — the driver merges it into
        the cluster aggregator with counter-reset protection."""
        # canary governance ticks here, off the request path: one rolling
        # window comparison per heartbeat interval (auto-rollback fires
        # even on a worker receiving no canary traffic of its own)
        try:
            _get_model_registry().check_canaries()
        except Exception as exc:
            _log_event("canary_check_failed", worker_id=self.worker_id,
                       error=repr(exc))
        payload = {"worker_id": self.worker_id,
                   "digest": self.server.health_digest()}
        interval = snapshot_interval()
        now = time.monotonic()
        send_telemetry = (interval >= 0
                          and (interval == 0
                               or now - self._last_telemetry_t >= interval))
        if send_telemetry:
            payload["telemetry"] = worker_snapshot()
        try:
            out = _http_json(self.driver_url + "/heartbeat", payload,
                             site="heartbeat").get("known", False)
        except Exception:
            return False
        if send_telemetry and out:
            self._last_telemetry_t = now
        return out

    # -- engine surface ------------------------------------------------------
    def get_batch(self, max_rows: int, timeout: float = 0.1
                  ) -> List[Tuple[str, CachedRequest]]:
        return [(self.worker_id, c)
                for c in self.server.get_batch(max_rows, timeout)]

    # -- reply routing -------------------------------------------------------
    def reply(self, owner_id: str, request_id: str,
              response: HTTPResponseData) -> bool:
        """Reply to a request parked on ``owner_id`` — locally or over HTTP
        (parity: ``HTTPSourceStateHolder.getServer(ip).replyTo``)."""
        if owner_id == self.worker_id:
            return self.server.reply(request_id, response)
        addr = self._peers.get(owner_id)
        if addr is None:
            try:
                self.refresh_peers()
            except Exception:
                return False
            addr = self._peers.get(owner_id)
            if addr is None:
                return False
        try:
            out = _http_json(addr + "/_reply",
                             {"request_id": request_id,
                              "response": response.to_dict()},
                             breaker=breaker_for(addr))
        except Exception:
            # same contract as the local branch: an already-answered /
            # timed-out / unreachable target is False, never an exception
            return False
        return bool(out.get("ok"))

    def _handle_remote_reply(self, req: HTTPRequestData) -> HTTPResponseData:
        payload = json.loads(req.entity.content if req.entity else b"{}")
        # server.reply is where the request's root span closes (exactly
        # once, on THIS owning worker) and its counters tick — the hop
        # itself only logs, so forwarded replies aren't double-billed
        ok = self.server.reply(payload["request_id"],
                               HTTPResponseData.from_dict(payload["response"]))
        _log_event("remote_reply", worker_id=self.worker_id,
                   request_id=payload.get("request_id"), ok=ok)
        return HTTPResponseData(
            entity=EntityData.from_string(json.dumps({"ok": ok})),
            status_line=StatusLineData(status_code=200 if ok else 404))

    # -- session adoption (failover / drain handoff) -------------------------
    def adopt_sessions(self, payload: dict) -> dict:
        """Accept sessions handed over by the driver/cluster.

        Payload: ``{"sessions": [{"session": <canonical session>,
        "kv": <page blob or null>}], "mode": "warm"|"cold", "from": id}``.
        With :attr:`adopt_handler` set (a decoder harness binding
        ``ContinuousDecoder.restore_session``), the whole payload is
        delegated there. The default journals each session into this
        worker's own journal — write-ahead, so an adopted session survives
        a second failure before any engine picks it up — and records it in
        :attr:`adopted_sessions`.
        """
        entries = payload.get("sessions") or []
        mode = payload.get("mode", "cold")
        if self.adopt_handler is not None:
            out = self.adopt_handler(payload)
            if isinstance(out, dict):
                return out
            return {"ok": True, "adopted": len(entries), "mode": mode,
                    "worker": self.worker_id}
        adopted = 0
        journal = self.server._journal
        for entry in entries:
            sess = entry.get("session") or {}
            sid = str(sess.get("id") or "")
            if not sid:
                continue
            if journal is not None:
                journal.record_session(sid, sess.get("prompt") or [],
                                       sess.get("params") or {},
                                       phash=sess.get("phash"))
                emitted = sess.get("emitted") or []
                if emitted:
                    journal.record_session_tokens(sid, emitted)
            self.adopted_sessions.append(entry)
            adopted += 1
        _log_event("sessions_adopted", worker_id=self.worker_id,
                   n=adopted, mode=mode, source=payload.get("from"))
        return {"ok": True, "adopted": adopted, "mode": mode,
                "worker": self.worker_id}

    def _handle_adopt(self, req: HTTPRequestData) -> HTTPResponseData:
        payload = json.loads(req.entity.content if req.entity else b"{}")
        try:
            out = self.adopt_sessions(payload)
        except Exception as exc:
            body = json.dumps({"ok": False, "error": repr(exc)})
            return HTTPResponseData(
                entity=EntityData.from_string(body),
                status_line=StatusLineData(status_code=500))
        return HTTPResponseData(
            entity=EntityData.from_string(json.dumps(out)),
            status_line=StatusLineData(status_code=200))

    # -- request forwarding (load balancing) ---------------------------------
    _FWD_PREFIX = "/_forward"
    _FWD_HDR = "X-Mmlspark-Original-Method"

    def _handle_forwarded(self, req: HTTPRequestData) -> HTTPResponseData:
        # restore the client's original path/query and method before parking
        if req.url.startswith(self._FWD_PREFIX):
            req.url = req.url[len(self._FWD_PREFIX):] or "/"
        for h in req.headers:
            if h.name == self._FWD_HDR:
                req.method = h.value
        req.headers = [h for h in req.headers if h.name != self._FWD_HDR]
        try:
            cached = self.server._enqueue(req)
        except Overloaded as exc:
            return HTTPResponseData(
                headers=[HeaderData("Retry-After", f"{exc.retry_after:g}")],
                status_line=StatusLineData(status_code=429,
                                           reason_phrase="overloaded"))
        resp = cached.wait(self.server.wait_budget(cached))
        if resp is None:
            return HTTPResponseData(
                status_line=StatusLineData(status_code=504,
                                           reason_phrase="forwarded timeout"))
        return resp

    def enable_forwarding(self) -> None:
        """Engine detached: forward public requests round-robin to peers
        instead of parking them (parity: load balancer ``:679-687``)."""
        self.has_engine = False
        self.server.control_routes["/"] = self._forward_out

    def disable_forwarding(self) -> None:
        self.has_engine = True
        self.server.control_routes.pop("/", None)

    def _note_forward(self, worker_id: str, delta: int) -> None:
        with self._lock:
            n = self._fwd_inflight.get(worker_id, 0) + delta
            if n > 0:
                self._fwd_inflight[worker_id] = n
            else:
                self._fwd_inflight.pop(worker_id, None)

    def _forward_candidates(self, req: HTTPRequestData
                            ) -> List[Tuple[str, str]]:
        """Peer try-order for one forwarded request as ``(worker_id,
        address)`` pairs. Requests carrying a KV-prefix affinity key
        (``X-Mmlspark-Prefix``, the hex of ``PagedKVPool.prefix_hash``)
        go ring-first: the owning worker's pool already holds their
        shared-prefix pages, with bounded-load fallback to the next ring
        position when the owner is saturated. Unkeyed requests keep the
        round-robin rotation."""
        affinity = None
        for h in req.headers:
            if h.name.lower() == AFFINITY_HEADER.lower():
                affinity = h.value.strip() or None
        with self._lock:
            peer_map = dict(self._peers)
            start = self._rr
            self._rr += 1
            load = dict(self._fwd_inflight)
        if not peer_map:
            return []
        if affinity is not None and len(self._ring):
            first = self._ring.route(affinity, load=load)
            order = [w for w in self._ring.preferred(affinity)
                     if w in peer_map]
            if first in peer_map:
                order = [first] + [w for w in order if w != first]
            if order:
                return [(w, peer_map[w]) for w in order]
        items = sorted(peer_map.items())
        return [items[(start + i) % len(items)] for i in range(len(items))]

    def _forward_out(self, req: HTTPRequestData) -> HTTPResponseData:
        candidates = self._forward_candidates(req)
        if not candidates:
            return HTTPResponseData(
                status_line=StatusLineData(status_code=503,
                                           reason_phrase="no peers"))
        # never wait past what the client has left: honor an inbound
        # deadline, else budget this hop with our own reply_timeout
        deadline = None
        for h in req.headers:
            if h.name.lower() == DEADLINE_HEADER.lower():
                deadline = Deadline.from_header(h.value)
        if deadline is None:
            deadline = Deadline.after(self.server.reply_timeout)
        body = req.entity.content if req.entity else None
        # carry the client's path/query, method, and headers across the hop
        base_hdrs = {h.name: h.value for h in req.headers
                     if h.name.lower() not in ("host", "content-length",
                                               "connection")}
        base_hdrs[self._FWD_HDR] = req.method
        injector = get_injector()
        # try each peer at most once, in candidate order, skipping open
        # circuits; 502 only once every peer has been exhausted
        for wid, addr in candidates:
            brk = breaker_for(addr)
            remaining = deadline.remaining()
            if remaining <= 0:
                return HTTPResponseData(
                    status_line=StatusLineData(status_code=504,
                                               reason_phrase="deadline"))
            if not brk.allow():
                continue
            hop_hdrs = dict(base_hdrs)
            hop_hdrs[DEADLINE_HEADER] = deadline.header_value()
            fwd = urllib.request.Request(
                addr + self._FWD_PREFIX + req.url, data=body,
                headers=hop_hdrs, method="POST" if body else "GET")
            self._note_forward(wid, +1)
            try:
                if injector.enabled:
                    injector.fire("peer_http")
                # the peer enforces the deadline (parks at most `remaining`);
                # the socket timeout is only a dead-peer guard, and needs
                # slack so the peer's own 504 arrives instead of racing it
                with urllib.request.urlopen(fwd, timeout=remaining + 1.0) as r:
                    payload = r.read()
                    brk.record_success()
                    return HTTPResponseData(
                        entity=EntityData(content=payload,
                                          content_length=len(payload)),
                        status_line=StatusLineData(status_code=r.status))
            except urllib.error.HTTPError as e:
                # the peer answered (504/429/...): relay it, don't fail over
                payload = e.read()
                brk.record_success()
                return HTTPResponseData(
                    entity=EntityData(content=payload,
                                      content_length=len(payload)),
                    status_line=StatusLineData(status_code=e.code))
            except Exception as exc:
                brk.record_failure()
                _tracing.add_event("forward_failover", peer=addr,
                                   error=type(exc).__name__)
            finally:
                self._note_forward(wid, -1)
        return HTTPResponseData(
            status_line=StatusLineData(status_code=502,
                                       reason_phrase="no reachable peer"))

    def close(self, deregister: bool = True) -> None:
        self._hb_stop.set()
        if deregister:
            try:
                _http_json(self.driver_url + "/deregister",
                           {"worker_id": self.worker_id}, site="register")
            except Exception as exc:
                # best-effort on shutdown (liveness pruning will finish the
                # job), but leave a trace for anyone chasing ghosts
                _log_event("deregister_failed", worker_id=self.worker_id,
                           error=repr(exc))
        self.server.close()
        self._hb_thread.join(timeout=2)


class ServingCluster:
    """N distributed workers + driver registry in one process — the test
    harness shape (reference tests distributed serving in local mode too,
    SURVEY §4). The aggregate ``get_batch``/``reply`` pair is the
    distributed source/sink surface an engine loop drives."""

    def __init__(self, n_workers: int, reply_timeout: float = 60.0,
                 max_queue: int = 10_000,
                 liveness_interval: Optional[float] = None,
                 heartbeat_interval: float = 10.0,
                 journal_dir: Optional[str] = None):
        self.driver = DriverRegistry(liveness_interval=liveness_interval)
        #: worker id → journal path (survives the worker object: the dead
        #: worker's journal is what cold reassignment scans)
        self._journal_paths: Dict[str, str] = {}
        self.workers: List[DistributedWorker] = []
        for i in range(n_workers):
            wid = f"worker-{i}"
            jp = None
            if journal_dir is not None:
                jp = os.path.join(journal_dir, f"{wid}.journal")
                self._journal_paths[wid] = jp
            self.workers.append(
                DistributedWorker(self.driver.url, wid,
                                  reply_timeout=reply_timeout,
                                  max_queue=max_queue,
                                  heartbeat_interval=heartbeat_interval,
                                  journal_path=jp))
        for w in self.workers:
            w.refresh_peers()
        # failover: a sweeper dead-verdict evicts the worker from routing;
        # this callback evicts it from every survivor's ring (refresh) and
        # replays its journaled sessions onto a survivor via /_adopt
        self.driver.add_dead_callback(self._on_worker_dead)

    def worker(self, worker_id: str) -> DistributedWorker:
        for w in self.workers:
            if w.worker_id == worker_id:
                return w
        raise KeyError(worker_id)

    def get_batch(self, max_rows: int, timeout: float = 0.05
                  ) -> List[Tuple[str, CachedRequest]]:
        # non-blocking sweep over every worker; one short sleep only if the
        # whole cluster is idle (a per-worker blocking get would add
        # N*timeout dead time to each poll)
        def sweep():
            got: List[Tuple[str, CachedRequest]] = []
            for w in self.workers:
                if not w.has_engine:
                    continue
                got.extend(w.get_batch(max_rows - len(got), timeout=0.0))
                if len(got) >= max_rows:
                    break
            return got

        out = sweep()
        if not out and timeout > 0:
            time.sleep(timeout)
            out = sweep()
        return out

    def reply(self, owner_id: str, request_id: str,
              response: HTTPResponseData) -> bool:
        # any live worker can route the reply; prefer the owner directly
        try:
            return self.worker(owner_id).server.reply(request_id, response)
        except KeyError:
            pass
        # unknown owner (registry drift / restarted elsewhere): route via
        # the first worker whose server is still open — a closed worker
        # can't speak HTTP to the owner anymore
        for w in self.workers:
            if not w.server.closed:
                return w.reply(owner_id, request_id, response)
        return False

    def scorecard(self) -> dict:
        """Cluster SLO scorecard from the driver's federation aggregator,
        with per-worker health digests attached (the in-process twin of
        ``GET /debug/cluster``)."""
        card = dict(self.driver.aggregator.scorecard())
        card["worker_health"] = self.driver.workers()
        return card

    def restart_worker(self, worker_id: str,
                       reply_timeout: Optional[float] = None
                       ) -> DistributedWorker:
        """Chaos/ops helper: kill one worker ungracefully (no deregister —
        a crash doesn't say goodbye) and re-register a replacement under
        the SAME id, exercising the recovery contract. The replacement
        reopens the same journal, so the dead incarnation's sessions are
        replayable on it (``scan_sessions``/``replay_sessions``)."""
        for i, w in enumerate(self.workers):
            if w.worker_id != worker_id:
                continue
            w.close(deregister=False)
            replacement = DistributedWorker(
                self.driver.url, worker_id,
                reply_timeout=(reply_timeout if reply_timeout is not None
                               else w.server.reply_timeout),
                max_queue=w.max_queue,
                journal_path=self._journal_paths.get(worker_id))
            self.workers[i] = replacement
            for peer in self.workers:
                try:
                    peer.refresh_peers()
                except Exception as exc:
                    _log_event("refresh_peers_failed",
                               worker_id=peer.worker_id, error=repr(exc))
            return replacement
        raise KeyError(worker_id)

    # -- session failover --------------------------------------------------
    def _survivors(self, exclude: str) -> List[DistributedWorker]:
        return [w for w in self.workers
                if w.worker_id != exclude and not w.server.closed]

    def _on_worker_dead(self, worker_id: str, info: dict) -> None:
        """Sweeper dead-verdict hook: the registry already evicted the
        worker from the routing table; refresh every survivor (ring
        eviction) and cold-reassign the dead worker's journaled sessions."""
        survivors = self._survivors(worker_id)
        for w in survivors:
            try:
                w.refresh_peers()
            except Exception as exc:
                _log_event("refresh_peers_failed", worker_id=w.worker_id,
                           error=repr(exc))
        self.reassign_sessions(worker_id, survivors=survivors)

    def reassign_sessions(self, worker_id: str,
                          survivors: Optional[List[DistributedWorker]] = None
                          ) -> dict:
        """Cold path: scan the (dead) worker's journal for live sessions
        and replay them onto a survivor over ``/_adopt``. Read-only on the
        journal — safe while the dead incarnation's fd is still open."""
        path = self._journal_paths.get(worker_id)
        if path is None or not os.path.exists(path):
            return {"ok": True, "adopted": 0, "mode": "cold"}
        try:
            sessions = ServingJournal.scan_sessions(path)
        except Exception as exc:
            _log_event("session_reassign_failed", worker_id=worker_id,
                       error=repr(exc))
            return {"ok": False, "adopted": 0, "error": repr(exc)}
        if not sessions:
            return {"ok": True, "adopted": 0, "mode": "cold"}
        survivors = (survivors if survivors is not None
                     else self._survivors(worker_id))
        if not survivors:
            _log_event("session_reassign_failed", worker_id=worker_id,
                       error="no surviving workers")
            return {"ok": False, "adopted": 0, "error": "no survivors"}
        target = survivors[0]
        # scan_sessions keys by id; the canonical per-session form the
        # adopter expects carries it inline
        payload = {"sessions": [{"session": dict(s, id=sid), "kv": None}
                                for sid, s in sessions.items()],
                   "mode": "cold", "from": worker_id}
        try:
            out = _http_json(target.advertised_address + "/_adopt", payload,
                             site="peer_http")
        except Exception as exc:
            _log_event("session_reassign_failed", worker_id=worker_id,
                       error=repr(exc))
            return {"ok": False, "adopted": 0, "error": repr(exc)}
        _log_event("sessions_reassigned", worker_id=worker_id,
                   target=target.worker_id, n=out.get("adopted"))
        return out

    def drain_worker(self, worker_id: str,
                     target_id: Optional[str] = None) -> dict:
        """Graceful drain: mark the worker draining (no new routed traffic),
        hand its live sessions to a survivor over ``/_adopt`` — warm by
        default (exported KV page blobs, zero recompute on the receiver),
        cold under ``MMLSPARK_TPU_ADOPT_POLICY=cold`` — then deregister and
        retire the worker. Preserves the at-most-once reply edge: parked
        requests drain on the old worker; only *sessions* move."""
        w = self.worker(worker_id)
        self.driver.mark_draining(worker_id)
        policy = _adopt_policy()
        entries: List[dict] = []
        if w.session_exporter is not None:
            entries = list(w.session_exporter() or [])
        if policy == "cold":
            entries = [{"session": e.get("session"), "kv": None}
                       for e in entries]
        out = {"ok": True, "adopted": 0, "mode": policy}
        if entries:
            survivors = self._survivors(worker_id)
            if not survivors:
                raise RuntimeError(
                    f"drain {worker_id}: no surviving worker to adopt "
                    f"{len(entries)} session(s)")
            target = (self.worker(target_id) if target_id is not None
                      else survivors[0])
            out = _http_json(target.advertised_address + "/_adopt",
                             {"sessions": entries, "mode": policy,
                              "from": worker_id},
                             site="peer_http")
        w.close(deregister=True)
        self.workers = [x for x in self.workers if x.worker_id != worker_id]
        for peer in self.workers:
            try:
                peer.refresh_peers()
            except Exception as exc:
                _log_event("refresh_peers_failed", worker_id=peer.worker_id,
                           error=repr(exc))
        _log_event("worker_drained", worker_id=worker_id,
                   adopted=out.get("adopted"), mode=policy)
        return out

    def close(self) -> None:
        for w in self.workers:
            w.close()
        self.driver.close()
