"""Paged KV pool — vLLM-style page-granular cache management for serving.

`serving/continuous.py` historically gave every slot a contiguous
``(H, max_len, hd)`` cache region: simple, but each slot pins worst-case
memory, prefix reuse needs a device copy into the slot, and a retiring
short request strands the tail of its region. This module supplies the
PagedAttention answer (PAPERS.md: vLLM) at the allocator level:

* **pages** — the physical cache is ``(num_pages, H, page_size, hd)`` per
  layer (`models/zoo/transformer.init_paged_cache`); requests are sized in
  pages for the tokens they can actually produce, not ``max_len``;
* **block tables** — each slot owns a row of physical page ids; attention
  gathers through it (`decode_step_paged` / `decode_window_paged`) and the
  result is bitwise-equal to the contiguous path;
* **copy-on-write prefix sharing** — whole pages of a cached prompt prefix
  are shared across requests by bumping a refcount; only the boundary page
  (which the new request will write into) is copied. Shared pages are
  never written: the first writable position of a joining request always
  lands at or past the copy boundary;
* **defrag on retire** — frees go back to a min-heap (lowest index first,
  keeping the live span dense); when the live span still drifts past the
  in-use count by `defrag threshold` pages, :meth:`compact` returns a
  permutation the engine applies with one device gather;
* **residency budgeting** — the pool's device bytes are pinned against the
  `ResidencyManager` budget (PR 6) via a fixed reservation, so KV pressure
  evicts LRU *data* columns instead of silently overcommitting HBM.

Physical page 0 is the **trash page**: never allocated, the redirect
target for inactive-row writebacks and for block-table entries past a
row's allocation. Its contents are garbage by design and never read
(attention masks trim reads to each row's true length).

The pool is host-side bookkeeping plus a handle to the device buffers;
all methods assume the caller (the engine) serializes access under its
own lock — there is no internal locking.
"""

from __future__ import annotations

import hashlib
import heapq
import time
import weakref
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax.numpy as jnp

from ..core.residency import get_residency_manager
from ..observability import (charge as _ledger_charge,
                             counter as _metric_counter,
                             gauge as _metric_gauge)

__all__ = ["PagedKVPool", "PoolExhausted", "KVAutotuner", "prefix_hash",
           "AFFINITY_HEADER", "affinity_headers"]

M_PAGES_TOTAL = _metric_gauge(
    "mmlspark_kvpool_pages_total",
    "Physical KV pages in the pool (excluding the trash page)")
M_PAGES_IN_USE = _metric_gauge(
    "mmlspark_kvpool_pages_in_use",
    "KV pages currently referenced by a slot or a cached prefix")
M_PREFIX_SHARE_HITS = _metric_counter(
    "mmlspark_kvpool_prefix_share_hits_total",
    "Physical pages shared into an admitted request from a cached prefix "
    "(each shared page counts once per acquiring request)")
M_DEFRAG_MOVES = _metric_counter(
    "mmlspark_kvpool_defrag_moves_total",
    "Live pages relocated by compaction gathers")
M_PREFILL_CHUNKS = _metric_counter(
    "mmlspark_kvpool_prefill_chunks_total",
    "Prefill chunks executed by the chunked-prefill scheduler")
M_ALLOC_FAILURES = _metric_counter(
    "mmlspark_kvpool_alloc_failures_total",
    "Page allocations that failed even after prefix eviction")
M_AUTOTUNE_GAMMA = _metric_gauge(
    "mmlspark_kvpool_autotune_gamma",
    "Current speculative draft length chosen by the KV autotuner")
M_AUTOTUNE_CHUNK = _metric_gauge(
    "mmlspark_kvpool_autotune_chunk_budget",
    "Current prefill chunk budget (tokens) chosen by the KV autotuner")
M_AUTOTUNE_DEPTH = _metric_gauge(
    "mmlspark_kvpool_autotune_pipeline_depth",
    "Current decode pipeline depth (in-flight steps) chosen by the KV "
    "autotuner")
M_GATHER_BYTES = _metric_counter(
    "mmlspark_kvpool_gather_bytes_total",
    "HBM bytes moved by gather-impl paged attention materializing "
    "contiguous K/V before attending (0 under the Pallas kernel, which "
    "reads pages in place)")
M_KERNEL_TICKS = _metric_counter(
    "mmlspark_kvpool_kernel_ticks_total",
    "Paged-attention decode calls dispatched, by implementation",
    labelnames=("impl",))


def prefix_hash(tokens: Sequence[int]) -> str:
    """Stable content hash for a prompt prefix (the prefix-registry key)."""
    h = hashlib.sha1()
    h.update(np.asarray(tokens, np.int64).tobytes())
    return h.hexdigest()


#: request header carrying a prefix-affinity key: clients stamp it with
#: :func:`prefix_hash` of their shared prompt prefix and the distributed
#: forwarder (serving/distributed.py) consistent-hashes it to the worker
#: whose pool already holds those pages
AFFINITY_HEADER = "X-Mmlspark-Prefix"


def affinity_headers(tokens: Sequence[int]) -> List[Tuple[str, str]]:
    """The routing header a session should attach so its requests land on
    the worker owning its shared-prefix pages — same hash the pool keys
    the prefix registry by, so routing affinity and page sharing agree."""
    return [(AFFINITY_HEADER, prefix_hash(tokens))]


class PoolExhausted(RuntimeError):
    """No free pages left — the engine sheds load or evicts prefixes."""


class PagedKVPool:
    """Page allocator + device buffer handle for one model's KV cache.

    ``buffers`` is the per-layer list of ``{"k","v"}`` page arrays the
    engine threads through its jitted steps (reassigning after every
    dispatch, since XLA returns fresh buffers). Everything else is host
    bookkeeping: a free min-heap over pages ``[1, num_pages)``, per-page
    refcounts, and the shared-prefix registry.
    """

    def __init__(self, cfg, *, num_pages: int, page_size: int,
                 kv_dtype: Optional[str] = None, make_buffer=None,
                 residency: bool = True, sharding=None):
        from ..ops.kv_quant import (SCALE_DTYPE, kv_store_dtype,
                                    resolve_kv_dtype)
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is reserved)")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.cfg = cfg
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        hd = cfg.d_model // cfg.heads
        shape = (self.num_pages, cfg.heads, self.page_size, hd)
        #: canonical quantized-page dtype name ("int8"/"fp8") or None for
        #: bf16 pages (the byte-exact oracle representation)
        self.kv_dtype = resolve_kv_dtype(kv_dtype)
        store = kv_store_dtype(self.kv_dtype)
        #: the jnp dtype K/V VALUES are stored in — what page alignment,
        #: residency accounting and HBM byte math must all be sized to
        self.value_dtype = cfg.dtype if store is None else store
        self.scale_dtype = None if store is None else SCALE_DTYPE
        #: how the page arrays lay out on a mesh (None = single-device).
        #: Under tensor parallelism this is P(None, "tp", None, None) —
        #: heads shard, the page dimension stays a shared allocator arena,
        #: so alloc/free/block tables/CoW/compact() remain device-count-
        #: invariant host bookkeeping and defrag's permutation gathers
        #: per-shard with no resharding round-trip. Quantized pools keep
        #: (num_pages, heads, page_size) scale arrays on P(None, "tp",
        #: None) — scales shard with the heads they rescale.
        self.pool_sharding = sharding
        self._mk = make_buffer or (lambda s, d: jnp.zeros(s, d))
        self._shape = shape
        self._scale_shape = shape[:3]
        self.buffers = self._make_buffers()
        self._free: List[int] = list(range(1, self.num_pages))
        heapq.heapify(self._free)
        self._refs = np.zeros(self.num_pages, np.int32)
        # page -> monotonic time it left the free heap; feeds the cost
        # ledger's kv_page_seconds charge when the last ref drops
        self._alloc_t: Dict[int, float] = {}
        # phash -> (pages tuple, prefix length in tokens)
        self._prefixes: Dict[str, Tuple[Tuple[int, ...], int]] = {}
        # phash -> registration count. Two engine keys whose prefixes are
        # token-identical hash to the same entry; the entry (and its page
        # refs) must survive until EVERY registering key has released it.
        self._prefix_regs: Dict[str, int] = {}
        self.high_water = 0
        self.stats = {"prefix_share_hits": 0, "defrag_moves": 0,
                      "prefill_chunks": 0, "alloc_failures": 0,
                      "gather_bytes": 0, "attn_ticks_kernel": 0,
                      "attn_ticks_gather": 0, "quant_error_probes": 0,
                      "quant_error_last": None, "quant_error_sum": 0.0,
                      "quant_error_max": 0.0}
        M_PAGES_TOTAL.set(self.num_pages - 1)
        M_PAGES_IN_USE.set(0)
        self._reservation = None
        if residency:
            mgr = get_residency_manager()
            token = mgr.reserve(self.device_bytes(), label="kv_pool")
            self._reservation = token
            self._finalizer = weakref.finalize(self, mgr.release, token)

    def _make_buffers(self):
        """Fresh per-layer page buffers through ``make_buffer`` (so mesh
        shardings apply): ``{"k","v"}`` in the value dtype, plus
        ``{"k_scale","v_scale"}`` when quantized."""
        layers = []
        for _ in range(self.cfg.layers):
            c = {"k": self._mk(self._shape, self.value_dtype),
                 "v": self._mk(self._shape, self.value_dtype)}
            if self.scale_dtype is not None:
                c["k_scale"] = self._mk(self._scale_shape, self.scale_dtype)
                c["v_scale"] = self._mk(self._scale_shape, self.scale_dtype)
            layers.append(c)
        return layers

    def device_bytes(self) -> int:
        """Exact device bytes of the pool's buffers — K+V values in the
        (possibly quantized) value dtype plus the scale arrays. This is
        what :func:`~mmlspark_tpu.core.residency.get_residency_manager`'s
        ``reserve()`` pins, so the budget sees the QUANTIZED itemsize: a
        fixed byte budget holds ~2x the pages under int8."""
        nbytes = (2 * self.cfg.layers * int(np.prod(self._shape)) *
                  jnp.dtype(self.value_dtype).itemsize)
        if self.scale_dtype is not None:
            nbytes += (2 * self.cfg.layers *
                       int(np.prod(self._scale_shape)) *
                       jnp.dtype(self.scale_dtype).itemsize)
        return nbytes

    def bytes_per_position(self) -> int:
        """HBM bytes one cached position costs across K+V and all layers
        (values + scales) — the unit the engine's per-tick byte
        accounting multiplies out."""
        from ..ops.kv_quant import kv_bytes_per_position
        hd = self.cfg.d_model // self.cfg.heads
        return self.cfg.layers * kv_bytes_per_position(
            self.cfg.heads, hd, self.value_dtype,
            self.scale_dtype is not None)

    def note_quant_error(self, rms: float) -> None:
        """Record one sampled write-time roundtrip error (relative RMS of
        ``dequantize(quantize(rows))`` vs the bf16 rows — exactly the
        delta between what the kernel reads and what the byte-exact
        oracle would have read). The engine forwards the same sample to
        the SLO tracker under its model label."""
        rms = float(rms)
        self.stats["quant_error_probes"] += 1
        self.stats["quant_error_last"] = rms
        self.stats["quant_error_sum"] += rms
        self.stats["quant_error_max"] = max(
            self.stats["quant_error_max"], rms)

    # -- allocation ----------------------------------------------------------

    def pages_per_slot(self, length: int) -> int:
        """Pages needed to hold ``length`` cache positions."""
        return -(-int(length) // self.page_size)

    @property
    def pages_in_use(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    def alloc(self, n: int, *, count_failure: bool = True) -> List[int]:
        """Take ``n`` free pages (lowest physical index first — keeps the
        live span dense so compaction rarely triggers). Raises
        :class:`PoolExhausted` without partial effects.

        ``count_failure=False`` suppresses the failure stat/metric for
        callers that retry under prefix-eviction pressure — only the
        TERMINAL failure (nothing left to evict) should count as an
        ``alloc_failure`` (see :meth:`note_alloc_failure`)."""
        if n < 0:
            raise ValueError("alloc() needs n >= 0")
        if n > len(self._free):
            if count_failure:
                self.note_alloc_failure()
            raise PoolExhausted(
                f"need {n} pages, {len(self._free)} free "
                f"({self.pages_in_use}/{self.num_pages - 1} in use)")
        pages = [heapq.heappop(self._free) for _ in range(n)]
        self._refs[pages] += 1
        now = time.monotonic()
        for p in pages:
            self._alloc_t[p] = now
        self.high_water = max(self.high_water, self.pages_in_use)
        M_PAGES_IN_USE.set(self.pages_in_use)
        return pages

    def note_alloc_failure(self) -> None:
        """Record a terminal allocation failure — one that stood even
        after every evictable prefix was released."""
        self.stats["alloc_failures"] += 1
        M_ALLOC_FAILURES.inc()

    def incref(self, pages: Sequence[int]) -> None:
        for p in pages:
            if self._refs[p] <= 0:
                raise ValueError(f"incref of free page {p}")
        self._refs[list(pages)] += 1

    def free(self, pages: Sequence[int], *, cost_cls=None,
             cost_trace=None) -> None:
        """Drop one reference per page; refcount-0 pages return to the
        free heap. Sharing makes double-free detectable: freeing an
        already-free page raises.

        Pages whose LAST reference drops here charge their whole hold
        (pages x seconds since they left the free heap) to the cost
        ledger as ``kv_page_seconds`` — under ``cost_cls``/``cost_trace``
        when the caller knows the owning request (the decoder's slot
        release does), else the ambient trace context."""
        held = 0.0
        now = time.monotonic()
        for p in pages:
            p = int(p)
            if p <= 0 or p >= self.num_pages or self._refs[p] <= 0:
                raise ValueError(f"free of unallocated page {p}")
            self._refs[p] -= 1
            if self._refs[p] == 0:
                heapq.heappush(self._free, p)
                held += now - self._alloc_t.pop(p, now)
        M_PAGES_IN_USE.set(self.pages_in_use)
        if held > 0.0:
            _ledger_charge("kv_page_seconds", held, cls=cost_cls,
                           trace_id=cost_trace)

    # -- prefix sharing ------------------------------------------------------

    def register_prefix(self, phash: str, pages: Sequence[int],
                        plen: int) -> None:
        """Retain ``pages`` (incref) as the cached cache-content of a
        prompt prefix of ``plen`` tokens. Registrations are COUNTED per
        hash: a re-registration keeps the existing entry's pages but
        adds a release obligation, so the entry outlives every key that
        registered it (releasing one of two token-identical keys must
        not dangle the other)."""
        if phash in self._prefixes:
            self._prefix_regs[phash] += 1
            return
        pages = tuple(int(p) for p in pages)
        self.incref(pages)
        self._prefixes[phash] = (pages, int(plen))
        self._prefix_regs[phash] = 1

    def lookup_prefix(self, phash: str):
        """``(pages, plen)`` or None."""
        return self._prefixes.get(phash)

    def acquire_prefix(self, phash: str,
                       n_shared: int) -> Tuple[Tuple[int, ...], int]:
        """Share the first ``n_shared`` pages of a registered prefix into
        a request (incref — copy-on-write: the request never writes
        them). Returns the full (pages, plen) entry."""
        pages, plen = self._prefixes[phash]
        shared = pages[:n_shared]
        self.incref(shared)
        if shared:
            self.stats["prefix_share_hits"] += len(shared)
            M_PREFIX_SHARE_HITS.inc(len(shared))
        return pages, plen

    def release_prefix(self, phash: str) -> None:
        """Drop one registration of a prefix (no-op for unknown hashes);
        the entry's page references fall only with the LAST one."""
        regs = self._prefix_regs.get(phash)
        if regs is None:
            return
        if regs > 1:
            self._prefix_regs[phash] = regs - 1
            return
        del self._prefix_regs[phash]
        pages, _ = self._prefixes.pop(phash)
        self.free(pages)

    # -- defrag --------------------------------------------------------------

    def fragmentation(self) -> int:
        """Pages of dead space inside the live span: how far the highest
        live page sits past where dense packing would put it."""
        live = np.nonzero(self._refs[1:] > 0)[0]
        if live.size == 0:
            return 0
        return int(live[-1] + 1) - int(live.size)

    def should_compact(self, threshold: int) -> bool:
        return self.fragmentation() >= max(1, int(threshold))

    def compact(self) -> Optional[np.ndarray]:
        """Pack live pages down to ``[1, n_live]``. Returns ``remap``
        (old physical id -> new, a full permutation of ``[0, num_pages)``
        with ``remap[0] == 0``) for the engine to (a) gather the device
        buffers with its inverse and (b) rewrite block tables and every
        host page list it holds — or None when nothing would move.
        Internal refcounts, the free heap and the prefix registry are
        rewritten here."""
        live = (np.nonzero(self._refs > 0)[0]).astype(np.int64)
        remap = np.zeros(self.num_pages, np.int64)
        nxt = 1
        moved = 0
        for old in live:
            if old == 0:
                continue
            remap[old] = nxt
            if old != nxt:
                moved += 1
            nxt += 1
        if moved == 0:
            return None
        # dead pages fill the remainder in index order (their contents are
        # garbage either way; the permutation just has to be total)
        dead = [p for p in range(1, self.num_pages) if self._refs[p] == 0]
        for old in dead:
            remap[old] = nxt
            nxt += 1
        new_refs = np.zeros_like(self._refs)
        new_refs[remap] = self._refs
        self._refs = new_refs
        self._free = [int(remap[p]) for p in dead]
        heapq.heapify(self._free)
        self._prefixes = {
            h: (tuple(int(remap[p]) for p in pages), plen)
            for h, (pages, plen) in self._prefixes.items()}
        self._alloc_t = {int(remap[p]): t
                         for p, t in self._alloc_t.items()}
        self.stats["defrag_moves"] += moved
        M_DEFRAG_MOVES.inc(moved)
        return remap

    # -- session export / adopt ----------------------------------------------

    def export_session(self, pages: Sequence[int], *, length: int) -> dict:
        """Serialize one session's KV pages into a portable JSON-able blob.

        ``pages`` is the session's page list in *logical* (block-table)
        order — the caller runs the compact permutation first (the engine's
        ``_maybe_compact`` remap) and hands over the post-remap list, so
        the blob is position-ordered regardless of physical placement on
        this pool. Quant scale pools (int8/fp8) ride along per layer under
        the same page indices. ``length`` is the number of positions the
        pages actually hold (prompt + written tokens); the receiver uses
        it to rebuild the block-table row and resume mid-page."""
        import base64
        pages = [int(p) for p in pages]
        idx = jnp.asarray(np.asarray(pages, np.int32))
        data = []
        for c in self.buffers:
            entry = {}
            for key, buf in c.items():
                arr = np.asarray(buf[idx])
                entry[key] = base64.b64encode(arr.tobytes()).decode("ascii")
            data.append(entry)
        self.stats["sessions_exported"] = \
            self.stats.get("sessions_exported", 0) + 1
        return {
            "v": 1,
            "page_size": self.page_size,
            "n_pages": len(pages),
            "length": int(length),
            "kv_dtype": self.kv_dtype,
            "value_dtype": np.dtype(self.value_dtype).name,
            "scale_dtype": (np.dtype(self.scale_dtype).name
                            if self.scale_dtype is not None else None),
            "layers": int(self.cfg.layers),
            "page_shape": [int(x) for x in self._shape[1:]],
            "data": data,
        }

    def adopt_session(self, blob: dict) -> List[int]:
        """Allocate pages on THIS pool and scatter ``blob``'s contents into
        them (the warm-handoff receive side). Returns the new page list in
        the blob's logical order — the caller rebuilds its block-table row
        from it. Raises ``ValueError`` on a layout mismatch (page size,
        layer count, head geometry, quantization mode must agree) and
        ``PoolExhausted`` — with nothing leaked — when this pool lacks the
        pages."""
        import base64
        if blob.get("v") != 1:
            raise ValueError(f"unknown session blob version {blob.get('v')}")
        want = {
            "page_size": self.page_size,
            "kv_dtype": self.kv_dtype,
            "value_dtype": np.dtype(self.value_dtype).name,
            "scale_dtype": (np.dtype(self.scale_dtype).name
                            if self.scale_dtype is not None else None),
            "layers": int(self.cfg.layers),
            "page_shape": [int(x) for x in self._shape[1:]],
        }
        got = {k: blob.get(k) for k in want}
        if got != want:
            raise ValueError(
                f"session blob layout mismatch: blob {got} != pool {want}")
        n = int(blob["n_pages"])
        pages = self.alloc(n)
        try:
            idx = jnp.asarray(np.asarray(pages, np.int32))
            new_buffers = []
            for c, entry in zip(self.buffers, blob["data"]):
                nc = {}
                for key, buf in c.items():
                    dt = np.dtype(self.scale_dtype if key.endswith("_scale")
                                  else self.value_dtype)
                    tail = (self._scale_shape[1:]
                            if key.endswith("_scale") else self._shape[1:])
                    arr = np.frombuffer(
                        base64.b64decode(entry[key]),
                        dtype=dt).reshape((n,) + tuple(tail))
                    nc[key] = buf.at[idx].set(jnp.asarray(arr, buf.dtype))
                new_buffers.append(nc)
            self.buffers = new_buffers
        except Exception:
            self.free(pages)
            raise
        self.stats["sessions_adopted"] = \
            self.stats.get("sessions_adopted", 0) + 1
        return pages

    # -- misc ----------------------------------------------------------------

    def note_prefill_chunk(self, ntok: int) -> None:
        self.stats["prefill_chunks"] += 1
        M_PREFILL_CHUNKS.inc()

    def note_attn_tick(self, impl: str, *, calls: int = 1,
                       gather_bytes: int = 0) -> None:
        """Account one dispatched paged-attention batch: ``calls`` decode/
        window invocations under ``impl`` ("kernel" or "gather"), plus the
        HBM bytes the gather impl moved materializing contiguous K/V
        (always 0 under the kernel — it reads pages in place)."""
        key = f"attn_ticks_{impl}"
        self.stats[key] = self.stats.get(key, 0) + calls
        M_KERNEL_TICKS.inc(calls, impl=impl)
        if gather_bytes:
            self.stats["gather_bytes"] += gather_bytes
            M_GATHER_BYTES.inc(gather_bytes)

    # -- kernel page-layout contract -----------------------------------------

    @staticmethod
    def kernel_page_multiple(dtype) -> int:
        """Sublane tile the Pallas paged-attention kernel needs
        ``page_size`` to be a multiple of on a real TPU: 8 (f32),
        16 (bf16), 32 (int8) — the page dimension sits in the sublane
        slot of the kernel's ``(1, heads, page, head_dim)`` blocks."""
        from ..ops.paged_attention import sublane_multiple
        return sublane_multiple(dtype)

    @classmethod
    def kernel_aligned_page_size(cls, page_size: int, dtype) -> int:
        """``page_size`` rounded up to the kernel-tileable multiple for
        ``dtype`` (identity when it already complies). The engine applies
        this whenever the kernel impl runs on a real TPU; interpret mode
        (CPU CI) accepts any page size."""
        from ..ops.paged_attention import aligned_page_size
        return aligned_page_size(page_size, dtype)

    def reset(self) -> None:
        """Forget every allocation and re-zero the device buffers (the
        engine's abort path). Rebuilds through the construction-time
        ``make_buffer`` so mesh shardings survive a reset."""
        self.buffers = self._make_buffers()
        self._free = list(range(1, self.num_pages))
        heapq.heapify(self._free)
        self._refs[:] = 0
        self._alloc_t.clear()
        self._prefixes.clear()
        self._prefix_regs.clear()
        M_PAGES_IN_USE.set(0)

    def close(self) -> None:
        """Release the residency reservation early (also runs at GC)."""
        if self._reservation is not None:
            self._finalizer()
            self._reservation = None


class KVAutotuner:
    """Closed-loop tuner for speculative gamma and the prefill chunk budget.

    Observations arrive once per engine tick; every ``interval`` ticks the
    tuner turns the batch into two decisions:

    * **gamma** (speculative draft length) follows the measured acceptance
      rate. Each verify round emits ``accepted + 1`` tokens per live slot,
      so ``acc = (emitted/round_slots - 1) / gamma``. High acceptance
      (>= ``acc_hi``) means drafts are cheap wins -> gamma += 1 (up to
      ``gamma_max``); low acceptance (<= ``acc_lo``) means wasted verify
      width -> gamma -= 1 (floor 1). Changing gamma between rounds keeps
      greedy output token-identical (accepted tokens are the target's own
      argmax choices) and sampled output distributionally exact per round.
    * **chunk budget** follows slot occupancy. A mostly-idle pool
      (occupancy <= ``occ_lo``) can afford bigger prefill bites -> chunk
      doubles (cap ``chunk_max``); a saturated pool (>= ``occ_hi``) needs
      decode latency bounded tighter -> chunk halves (floor ``chunk_min``).
      The power-of-two ladder keeps the window-width compile set small.
    * **pipeline depth** (in-flight decode steps before the engine drains)
      follows the same occupancy signal, in the same direction as chunk and
      for the same reason: an idle pool hides dispatch latency behind a
      deeper pipeline -> depth += 1 (cap ``depth_max``); a saturated pool
      is throughput-bound on the chip anyway and every queued step adds a
      full step-time to p99 time-to-token -> depth -= 1 (floor
      ``depth_min``). Disabled when constructed with ``depth=None`` (the
      engine keeps its static depth).
    """

    def __init__(self, *, gamma: int, gamma_max: int, chunk: int,
                 chunk_min: int = 32, chunk_max: int = 1024,
                 interval: int = 32, acc_lo: float = 0.55,
                 acc_hi: float = 0.85, occ_lo: float = 0.25,
                 occ_hi: float = 0.75, depth: Optional[int] = None,
                 depth_min: int = 1, depth_max: int = 4):
        self.gamma = int(gamma)
        self.gamma_max = int(gamma_max)
        self.chunk = int(chunk)
        self.chunk_min = int(chunk_min)
        self.chunk_max = int(chunk_max)
        self.interval = max(1, int(interval))
        self.acc_lo, self.acc_hi = float(acc_lo), float(acc_hi)
        self.occ_lo, self.occ_hi = float(occ_lo), float(occ_hi)
        self.depth = None if depth is None else int(depth)
        self.depth_min = max(0, int(depth_min))
        self.depth_max = max(self.depth_min, int(depth_max))
        self.history: List[Dict] = []
        self._ticks = 0
        self._occ_sum = 0.0
        self._emitted0 = 0
        self._rounds0 = 0
        M_AUTOTUNE_GAMMA.set(self.gamma)
        M_AUTOTUNE_CHUNK.set(self.chunk)
        if self.depth is not None:
            M_AUTOTUNE_DEPTH.set(self.depth)

    def observe(self, live: int, slots: int, spec_emitted: Optional[int] = None,
                spec_round_slots: Optional[int] = None) -> None:
        """One engine tick: ``live`` occupied of ``slots`` total, plus the
        engine's cumulative speculative counters (deltas are taken here)."""
        self._ticks += 1
        self._occ_sum += live / max(1, slots)
        if self._ticks < self.interval:
            return
        occ = self._occ_sum / self._ticks
        self._ticks = 0
        self._occ_sum = 0.0
        if spec_emitted is not None and spec_round_slots is not None:
            d_emit = spec_emitted - self._emitted0
            d_rounds = spec_round_slots - self._rounds0
            self._emitted0, self._rounds0 = spec_emitted, spec_round_slots
            if d_rounds > 0 and self.gamma > 0:
                acc = (d_emit / d_rounds - 1.0) / self.gamma
                if acc >= self.acc_hi and self.gamma < self.gamma_max:
                    self._set_gamma(self.gamma + 1, acc)
                elif acc <= self.acc_lo and self.gamma > 1:
                    self._set_gamma(self.gamma - 1, acc)
        if occ <= self.occ_lo and self.chunk * 2 <= self.chunk_max:
            self._set_chunk(self.chunk * 2, occ)
        elif occ >= self.occ_hi and self.chunk // 2 >= self.chunk_min:
            self._set_chunk(self.chunk // 2, occ)
        if self.depth is not None:
            if occ <= self.occ_lo and self.depth + 1 <= self.depth_max:
                self._set_depth(self.depth + 1, occ)
            elif occ >= self.occ_hi and self.depth - 1 >= self.depth_min:
                self._set_depth(self.depth - 1, occ)

    def _set_gamma(self, g: int, acc: float) -> None:
        self.history.append({"knob": "gamma", "from": self.gamma, "to": g,
                             "acceptance": round(acc, 4)})
        self.gamma = g
        M_AUTOTUNE_GAMMA.set(g)

    def _set_chunk(self, c: int, occ: float) -> None:
        self.history.append({"knob": "chunk", "from": self.chunk, "to": c,
                             "occupancy": round(occ, 4)})
        self.chunk = c
        M_AUTOTUNE_CHUNK.set(c)

    def _set_depth(self, d: int, occ: float) -> None:
        self.history.append({"knob": "depth", "from": self.depth, "to": d,
                             "occupancy": round(occ, 4)})
        self.depth = d
        M_AUTOTUNE_DEPTH.set(d)
