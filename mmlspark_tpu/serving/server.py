"""Per-worker HTTP server with epoch-keyed queues and replay.

Parity: ``WorkerServer`` (``HTTPSourceV2.scala:476-697``) — a lightweight
HTTP server per worker process; incoming requests are parked in an
epoch-keyed queue (``:512-518``), handed to the engine as batches, and
answered later through a routing table (``replyTo``/``respondToHTTPExchange``,
``:536-554``). Unanswered requests of an epoch survive an engine restart and
are re-served (history rehydration, ``:489-506,556-568``).

Implementation: ``ThreadingHTTPServer`` (one thread per connection, parked on
a per-request ``threading.Event`` until the reply lands) — the Python shape
of the reference's ``com.sun.net.httpserver`` + blocked ``HttpExchange``.
"""

from __future__ import annotations

import asyncio
import logging
import os
import queue
import socket
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from ..io.http.schema import (EntityData, HeaderData, HTTPRequestData,
                              HTTPResponseData, StatusLineData)
from ..observability import (CONTENT_TYPE as _PROM_CONTENT_TYPE,
                             build_info as _build_info,
                             classify_route as _classify_route,
                             counter as _metric_counter,
                             gauge as _metric_gauge,
                             get_ledger as _get_ledger,
                             get_tracker as _get_tracker,
                             get_watchdog as _get_watchdog,
                             histogram as _metric_histogram,
                             log_event as _log_event,
                             process_uptime_seconds as _process_uptime,
                             register_hbm_gauges as _register_hbm_gauges,
                             render as _render_metrics)
from ..observability import tracing as _tracing
from ..observability.timeseries import (acquire_sampler as _acquire_sampler,
                                        get_alert_engine as _get_alert_engine,
                                        get_store as _get_ts_store,
                                        release_sampler as _release_sampler,
                                        render_sparklines as
                                        _render_sparklines)
from ..reliability import (Deadline, get_injector as _get_injector,
                           open_breakers as _open_breakers)
from ..reliability.lock_sanitizer import new_lock
from .admission import AdmissionQueue, TenantOverBudget
from .registry import get_registry as _get_model_registry

__all__ = ["CachedRequest", "Overloaded", "WorkerServer"]

# serving-plane metrics (docs/observability.md) — scraped at GET /metrics,
# which every WorkerServer answers as a built-in control route
_M_REQUESTS = _metric_counter(
    "mmlspark_serving_requests_total",
    "HTTP requests answered by the worker server",
    ("transport", "method", "code"))
_M_REQ_LATENCY = _metric_histogram(
    "mmlspark_serving_request_seconds",
    "End-to-end request latency: body read to reply written (streaming "
    "replies are observed at stream open)", ("transport",))
_M_QUEUE_DEPTH = _metric_gauge(
    "mmlspark_serving_queue_depth",
    "Requests parked in the epoch queue awaiting a dispatcher", ("port",))
_M_INFLIGHT = _metric_gauge(
    "mmlspark_serving_inflight_requests",
    "Requests accepted but not yet answered (routing-table size)",
    ("port",))
# same object the watchdog registers per-device callbacks on — declared
# here so health_digest can sum it without touching watchdog internals
_M_HBM_IN_USE = _metric_gauge(
    "mmlspark_device_hbm_bytes_in_use",
    "Device memory in use (memory_stats; backends without it expose "
    "nothing)", ("device",))
_M_SHED = _metric_counter(
    "mmlspark_requests_shed_total",
    "Requests rejected 429 by bounded-queue admission control")


_STREAM_TIMEOUT_EVENT = b'data: {"error": "stream reply timeout"}\n\n'


class Overloaded(RuntimeError):
    """The parked-request queue is full — the transports turn this into
    ``429 Too Many Requests`` + ``Retry-After`` (shed early rather than
    park unboundedly and 504 late)."""

    def __init__(self, retry_after: float = 1.0):
        super().__init__("serving queue full")
        self.retry_after = retry_after


def _entity_bytes(response) -> Optional[bytes]:
    """Reply body bytes for the shadow diff (None for streaming replies —
    stream content is unjoinable, the diff records only arrival)."""
    entity = getattr(response, "entity", None)
    content = getattr(entity, "content", None)
    return content if isinstance(content, bytes) else None


def _trace_headers(cached: Optional["CachedRequest"]
                   ) -> List[Tuple[str, str]]:
    """Response correlation headers for a queued request: the request id
    (the handle `reply` keys on) and the W3C traceparent of the root span,
    so callers can fetch the span tree from /debug/traces."""
    if cached is None or cached.trace_span is None:
        return []
    return [("X-Request-Id", cached.request_id),
            ("traceparent", _tracing.format_traceparent(cached.trace_span))]


class StreamingReply:
    """A reply delivered incrementally (Server-Sent Events by default).

    Returned by :meth:`WorkerServer.reply_stream`; the owning transport
    writes ``200`` + ``Content-Type: text/event-stream`` +
    ``Connection: close`` (no content length — the stream ends when the
    server closes it), then drains chunks as they arrive. ``send`` and
    ``close`` are callable from any thread; sends after ``close`` are
    dropped. Stream CONTENT is not journaled — the reply record marks the
    request answered when the stream opens (the documented at-most-once
    reply window applies to the whole stream).
    """

    _CLOSE = object()

    def __init__(self, content_type: str = "text/event-stream"):
        self.content_type = content_type
        self._q: "queue.Queue" = queue.Queue()
        self._notify = None
        self._lock = new_lock("serving.server.StreamingReply._lock")
        self._closed = False

    def send(self, data) -> None:
        if isinstance(data, str):
            data = data.encode("utf-8")
        with self._lock:
            if self._closed:
                return
            # _q is unbounded: put() never blocks, it only appends — the
            # lock pairs the closed-check with the enqueue
            self._q.put(bytes(data))  # tpulint: disable=TPU014
            notify = self._notify
        if notify is not None:
            notify()

    def send_event(self, payload) -> None:
        """One SSE ``data:`` event carrying a JSON payload."""
        import json as _json
        self.send(f"data: {_json.dumps(payload)}\n\n")

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            # unbounded queue — see send()
            self._q.put(StreamingReply._CLOSE)  # tpulint: disable=TPU014
            notify = self._notify
        if notify is not None:
            notify()

    # -- transport side -----------------------------------------------------
    def _register(self, notify) -> None:
        """Async transport: fire ``notify()`` (thread-safe) whenever a
        chunk lands; fires immediately if chunks are already queued."""
        with self._lock:
            self._notify = notify
            pending = not self._q.empty()
        if pending:
            notify()

    def _get(self, timeout: Optional[float]):
        """Blocking chunk fetch (threaded transport): bytes, the close
        sentinel, or None on timeout."""
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def _drain_nowait(self):
        out = []
        while True:
            try:
                out.append(self._q.get_nowait())
            except queue.Empty:
                return out


@dataclass
class CachedRequest:
    """Parity: ``CachedRequest`` — a parked exchange + its id."""
    request_id: str
    epoch: int
    request: HTTPRequestData
    #: True when rehydrated from the journal after a process restart — the
    #: original connection is gone; the reply is journaled, not delivered
    replayed: bool = False
    #: root span of this request's trace (observability/tracing.py); None
    #: for replayed requests (the original caller's connection is gone)
    trace_span: Optional[object] = field(default=None, repr=False)
    #: remaining-budget carried in from X-Mmlspark-Deadline (reliability/
    #: policy.py) — caps how long the transport parks this request
    deadline: Optional[Deadline] = field(default=None, repr=False)
    #: tenant from X-Mmlspark-Tenant (SLO/cost workload class dimension)
    tenant: str = "default"
    #: resolved model version ("name@version") from X-Mmlspark-Model via
    #: the registry; None for unversioned (single-model) requests
    model_label: Optional[str] = None
    #: True for a synthetic shadow mirror — never journaled, its reply is
    #: joined/diffed by the registry instead of reaching a caller
    shadow: bool = False
    #: monotonic enqueue timestamp — get_batch charges the ledger's
    #: queue_wait_seconds from it at dequeue
    enqueued_at: float = field(default_factory=time.monotonic, repr=False)
    _done: threading.Event = field(default_factory=threading.Event, repr=False)
    _response: Optional[HTTPResponseData] = field(default=None, repr=False)

    _cbs: List[object] = field(default_factory=list, repr=False)
    _cb_lock: threading.Lock = field(default_factory=threading.Lock,
                                     repr=False)

    def respond(self, response: HTTPResponseData) -> None:
        with self._cb_lock:
            self._response = response
            self._done.set()
            cbs = list(self._cbs)
            self._cbs.clear()
        for cb in cbs:
            cb(response)

    def add_done_callback(self, cb) -> None:
        """Fire ``cb(response)`` exactly once when the reply lands — the
        async transport's bridge out of dispatcher threads (and the
        shadow-traffic join). Multiple callbacks are supported; each
        fires once, in registration order. Safe against respond() racing
        the registration."""
        with self._cb_lock:
            if not self._done.is_set():
                self._cbs.append(cb)
                return
            response = self._response
        cb(response)

    def wait(self, timeout: Optional[float]) -> Optional[HTTPResponseData]:
        if self._done.wait(timeout):
            return self._response
        return None


class _Handler(BaseHTTPRequestHandler):
    server_version = "mmlspark-tpu-serving/1.0"
    protocol_version = "HTTP/1.1"
    # headers and body go out as separate sends; without TCP_NODELAY, Nagle
    # holds the body until the client's delayed ACK (~40 ms) on every
    # keep-alive request — the difference between 23 and 750 req/s/conn
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):
        # quiet on stderr, but not dropped: access lines (and the parse
        # errors BaseHTTPRequestHandler reports through log_error) become
        # structured DEBUG events — raise the mmlspark_tpu.events logger
        # level to see them, no code edit required
        try:
            line = fmt % args
        except Exception:
            line = fmt
        _log_event("http_access", level=logging.DEBUG,
                   client=self.client_address[0], line=line)

    def _read_body(self) -> bytes:
        te = (self.headers.get("Transfer-Encoding") or "").lower()
        if "chunked" in te:
            # drain chunked framing; leaving it unread would corrupt the
            # keep-alive connection for the next pipelined request
            chunks = []
            while True:
                size_line = self.rfile.readline(65536).strip()
                size = int(size_line.split(b";")[0] or b"0", 16)
                if size == 0:
                    while self.rfile.readline(65536) not in (b"\r\n", b"\n", b""):
                        pass  # trailers
                    break
                chunks.append(self.rfile.read(size))
                self.rfile.read(2)  # CRLF after each chunk
            return b"".join(chunks)
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length) if length else b""

    def _handle(self):
        ws: "WorkerServer" = self.server.worker_server  # type: ignore[attr-defined]
        t0 = time.perf_counter()
        try:
            body = self._read_body()
        except (ValueError, ConnectionError):
            self.send_response(400, "bad request body")
            self.send_header("Content-Length", "0")
            self.end_headers()
            self.close_connection = True
            ws._observe_request("threaded", self.command, 400,
                                time.perf_counter() - t0, path=self.path)
            return
        req = HTTPRequestData(
            url=self.path, method=self.command,
            headers=[HeaderData(k, v) for k, v in self.headers.items()],
            entity=EntityData(content=body, content_length=len(body)) if body else None)
        # control routes (internal cross-worker endpoints: reply forwarding,
        # request forwarding) answer synchronously, bypassing the queue
        cached = None
        ctrl = ws._control_route(self.path)
        if ctrl is not None:
            try:
                resp = ctrl(req)
            except Exception as e:  # control failures must not park forever
                resp = HTTPResponseData(
                    entity=EntityData.from_string(str(e)),
                    status_line=StatusLineData(status_code=500))
        else:
            try:
                cached = ws._enqueue(req)
            except Overloaded as e:
                self.send_response(429, "overloaded")
                self.send_header("Retry-After", f"{e.retry_after:g}")
                self.send_header("Content-Length", "0")
                self.end_headers()
                ws._observe_request("threaded", self.command, 429,
                                    time.perf_counter() - t0, path=self.path)
                return
            except Exception as e:
                # enqueue failure (journal append, injected fault): answer
                # 500 instead of killing this connection's handler thread
                body500 = str(e).encode()
                self.send_response(500, "enqueue failed")
                self.send_header("Content-Length", str(len(body500)))
                self.end_headers()
                self.wfile.write(body500)
                ws._observe_request("threaded", self.command, 500,
                                    time.perf_counter() - t0, path=self.path)
                return
            resp = cached.wait(ws.wait_budget(cached))
        if resp is None:
            if cached is not None and cached.trace_span is not None:
                cached.trace_span.end(status=504)
            self.send_response(504, "serving reply timeout")
            for name, value in _trace_headers(cached):
                self.send_header(name, value)
            self.send_header("Content-Length", "0")
            self.end_headers()
            ws._observe_request("threaded", self.command, 504,
                                time.perf_counter() - t0, path=self.path,
                                trace_span=cached.trace_span
                                if cached is not None else None)
            return
        tspan = cached.trace_span if cached is not None else None
        if isinstance(resp, StreamingReply):
            # incremental reply: preamble now, chunks until close(); the
            # connection ends with the stream (no content length exists)
            ws._observe_request("threaded", self.command, 200,
                                time.perf_counter() - t0, path=self.path,
                                trace_span=tspan)
            self.send_response(200)
            self.send_header("Content-Type", resp.content_type)
            self.send_header("Cache-Control", "no-store")
            for name, value in _trace_headers(cached):
                self.send_header(name, value)
            self.send_header("Connection", "close")
            self.end_headers()
            self.close_connection = True
            while True:
                chunk = resp._get(ws.reply_timeout)
                if chunk is StreamingReply._CLOSE:
                    break
                if chunk is None:
                    # per-chunk timeout: a silently truncated 200 would
                    # read as a short successful stream — emit an explicit
                    # final error event and stop accepting sends
                    resp.close()
                    chunk = _STREAM_TIMEOUT_EVENT
                try:
                    self.wfile.write(chunk)
                    self.wfile.flush()
                except (ConnectionError, BrokenPipeError):
                    break
                if chunk is _STREAM_TIMEOUT_EVENT:
                    break
            return
        payload = resp.entity.content if resp.entity else b""
        ws._observe_request("threaded", self.command,
                            resp.status_line.status_code,
                            time.perf_counter() - t0, path=self.path,
                            trace_span=tspan)
        self.send_response(resp.status_line.status_code,
                           resp.status_line.reason_phrase or None)
        sent = {h.name.lower() for h in resp.headers}
        for h in resp.headers:
            if h.name.lower() not in ("content-length", "connection"):
                self.send_header(h.name, h.value)
        for name, value in _trace_headers(cached):
            if name.lower() not in sent:
                self.send_header(name, value)
        if "content-type" not in sent and payload:
            self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        if payload:
            self.wfile.write(payload)

    do_GET = do_POST = do_PUT = do_DELETE = _handle


class _AsyncHTTPServer:
    """Event-loop transport: ALL connections multiplexed on one asyncio IO
    thread; replies cross from dispatcher threads via
    ``call_soon_threadsafe``.

    The thread-per-connection transport collapses past ~50 concurrent
    keep-alive connections (GIL convoy across 64 handler threads measured
    ~150 req/s with multi-second stalls); the reference's
    ``com.sun.net.httpserver`` is likewise selector-based rather than
    thread-per-connection (``HTTPSourceV2.scala:476-697``)."""

    def __init__(self, ws: "WorkerServer", host: str, port: int):
        self._ws = ws
        self._host = host
        self._loop = asyncio.new_event_loop()
        self._ready = threading.Event()
        self._server = None
        self._error: Optional[BaseException] = None
        self.port: Optional[int] = None
        self._thread = threading.Thread(target=self._run, args=(port,),
                                        name="serving-aio", daemon=True)
        self._thread.start()
        if not self._ready.wait(10):
            raise RuntimeError("async serving transport failed to start")
        if self._error is not None:     # e.g. EADDRINUSE — surface the cause
            raise self._error

    def _run(self, port: int) -> None:
        asyncio.set_event_loop(self._loop)

        async def boot():
            self._server = await asyncio.start_server(
                self._handle_conn, self._host, port)
            self.port = self._server.sockets[0].getsockname()[1]

        try:
            self._loop.run_until_complete(boot())
        except BaseException as e:
            self._error = e
            self._loop.close()
            self._ready.set()
            return
        self._ready.set()
        try:
            self._loop.run_forever()
        finally:
            self._loop.close()

    async def _read_request(self, reader, writer):
        line = await reader.readline()
        if not line or line in (b"\r\n", b"\n"):
            return None
        parts = line.decode("latin-1").rstrip("\r\n").split()
        if len(parts) < 2:
            return None
        method, path = parts[0], parts[1]
        headers, hmap = [], {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            if len(headers) >= 100:     # http.client's own header cap
                raise ValueError("got more than 100 headers")
            k, _, v = h.decode("latin-1").partition(":")
            k, v = k.strip(), v.strip()
            headers.append(HeaderData(k, v))
            hmap[k.lower()] = v
        if "100-continue" in hmap.get("expect", "").lower():
            # curl (any body > 1 KB) parks until the interim response —
            # the threaded transport's handle_expect_100 equivalent
            writer.write(b"HTTP/1.1 100 Continue\r\n\r\n")
            await writer.drain()
        if "chunked" in hmap.get("transfer-encoding", "").lower():
            chunks = []
            while True:
                size_line = (await reader.readline()).strip()
                size = int(size_line.split(b";")[0] or b"0", 16)
                if size == 0:
                    while (await reader.readline()) not in (b"\r\n", b"\n",
                                                            b""):
                        pass    # trailers
                    break
                chunks.append(await reader.readexactly(size))
                await reader.readexactly(2)     # CRLF after each chunk
            body = b"".join(chunks)
        else:
            length = int(hmap.get("content-length") or 0)
            body = await reader.readexactly(length) if length else b""
        req = HTTPRequestData(
            url=path, method=method, headers=headers,
            entity=EntityData(content=body, content_length=len(body))
            if body else None)
        return req, hmap.get("connection", "").lower() == "close"

    @staticmethod
    def _render(resp: HTTPResponseData,
                extra_headers: List[Tuple[str, str]] = ()) -> bytes:
        """Serialize status + headers + body into ONE buffer (a single send
        — immune to the Nagle/delayed-ACK stall by construction)."""
        payload = resp.entity.content if resp.entity else b""
        status = resp.status_line.status_code
        reason = (resp.status_line.reason_phrase or "").replace("\r", "") \
            .replace("\n", "")
        lines = [f"HTTP/1.1 {status} {reason}".rstrip().encode("latin-1")]
        sent = set()
        for h in resp.headers:
            if h.name.lower() not in ("content-length", "connection"):
                lines.append(f"{h.name}: {h.value}".encode("latin-1"))
                sent.add(h.name.lower())
        for name, value in extra_headers:
            if name.lower() not in sent:
                lines.append(f"{name}: {value}".encode("latin-1"))
        if "content-type" not in sent and payload:
            lines.append(b"Content-Type: application/json")
        lines.append(f"Content-Length: {len(payload)}".encode("latin-1"))
        lines.append(b"")
        return b"\r\n".join(lines) + b"\r\n" + payload

    async def _handle_conn(self, reader, writer):
        sock = writer.get_extra_info("socket")
        if sock is not None:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        ws = self._ws
        try:
            while True:
                try:
                    parsed = await self._read_request(reader, writer)
                except (ValueError, asyncio.LimitOverrunError):
                    # malformed framing (bad Content-Length / chunk size /
                    # oversized header) — answer 400 like the threaded
                    # transport instead of silently resetting
                    writer.write(self._render(HTTPResponseData(
                        status_line=StatusLineData(
                            status_code=400,
                            reason_phrase="bad request body"))))
                    await writer.drain()
                    # no parsed request line — count it, skip the latency
                    # observation (t0 would include keep-alive idle time)
                    ws._observe_request("async", "?", 400, None)
                    break
                if parsed is None:
                    break
                req, close = parsed
                t0 = time.perf_counter()
                cached = None
                ctrl = ws._control_route(req.url)
                if ctrl is not None:
                    # control routes may block on cross-worker HTTP — keep
                    # them off the IO thread
                    try:
                        resp = await self._loop.run_in_executor(None, ctrl,
                                                                req)
                    except Exception as e:
                        resp = HTTPResponseData(
                            entity=EntityData.from_string(str(e)),
                            status_line=StatusLineData(status_code=500))
                else:
                    # enqueue off the IO thread: the bounded queue.put can
                    # block when parked requests hit max_queue, and a
                    # configured journal fsyncs per request — either would
                    # freeze EVERY multiplexed connection if run here. The
                    # executor provides natural backpressure instead.
                    try:
                        cached = await self._loop.run_in_executor(
                            None, ws._enqueue, req)
                    except Overloaded as e:
                        resp = HTTPResponseData(
                            headers=[HeaderData("Retry-After",
                                                f"{e.retry_after:g}")],
                            status_line=StatusLineData(
                                status_code=429,
                                reason_phrase="overloaded"))
                    except Exception as e:
                        # enqueue failure (journal append, injected fault)
                        # — answer 500, keep the connection multiplexing
                        resp = HTTPResponseData(
                            entity=EntityData.from_string(str(e)),
                            status_line=StatusLineData(status_code=500))
                    else:
                        fut = self._loop.create_future()

                        def _cb(response, fut=fut):
                            try:
                                self._loop.call_soon_threadsafe(
                                    lambda: None if fut.done()
                                    else fut.set_result(response))
                            except RuntimeError:
                                # loop already closed (shutdown race) — the
                                # reply has nowhere to go; don't kill the
                                # dispatcher thread delivering it
                                pass

                        cached.add_done_callback(_cb)
                        try:
                            resp = await asyncio.wait_for(
                                fut, ws.wait_budget(cached))
                        except asyncio.TimeoutError:
                            if cached.trace_span is not None:
                                cached.trace_span.end(status=504)
                            resp = HTTPResponseData(
                                status_line=StatusLineData(
                                    status_code=504,
                                    reason_phrase="serving reply timeout"))
                tspan = cached.trace_span if cached is not None else None
                echo = _trace_headers(cached)
                if isinstance(resp, StreamingReply):
                    ws._observe_request("async", req.method, 200,
                                        time.perf_counter() - t0,
                                        path=req.url, trace_span=tspan)
                    echo_raw = b"".join(
                        f"{n}: {v}\r\n".encode("latin-1") for n, v in echo)
                    writer.write(
                        b"HTTP/1.1 200 OK\r\n"
                        b"Content-Type: "
                        + resp.content_type.encode("ascii")
                        + b"\r\nCache-Control: no-store\r\n"
                        + echo_raw
                        + b"Connection: close\r\n\r\n")
                    await writer.drain()
                    # chunks cross from dispatcher threads via a
                    # call_soon_threadsafe-set event; the IO thread never
                    # blocks on the stream
                    ev = asyncio.Event()
                    resp._register(lambda: self._loop.call_soon_threadsafe(
                        ev.set))
                    ended = False
                    while not ended:
                        try:
                            await asyncio.wait_for(ev.wait(),
                                                   ws.reply_timeout)
                        except asyncio.TimeoutError:
                            # explicit final error event — a silently
                            # truncated 200 would read as success
                            resp.close()
                            writer.write(_STREAM_TIMEOUT_EVENT)
                            await writer.drain()
                            break
                        ev.clear()
                        for chunk in resp._drain_nowait():
                            if chunk is StreamingReply._CLOSE:
                                ended = True
                                break
                            writer.write(chunk)
                        await writer.drain()
                    break                      # stream ends the connection
                ws._observe_request("async", req.method,
                                    resp.status_line.status_code,
                                    time.perf_counter() - t0,
                                    path=req.url, trace_span=tspan)
                writer.write(self._render(resp, echo))
                await writer.drain()
                if close:
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
            # per-connection teardown race on an already-reset socket:
            # nothing to recover, and an event per closed keep-alive
            # connection would be pure noise
            except Exception:  # tpulint: disable=TPU009
                pass

    def close(self) -> None:
        def _stop():
            if self._server is not None:
                self._server.close()
            self._loop.stop()

        self._loop.call_soon_threadsafe(_stop)
        self._thread.join(timeout=5)


class WorkerServer:
    """HTTP listener + epoch request queue + reply routing table.

    ``transport="threaded"`` (default) is thread-per-connection;
    ``transport="async"`` multiplexes every connection on one asyncio IO
    thread — the shape to use past ~50 concurrent connections."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 api_path: str = "/", reply_timeout: float = 60.0,
                 max_queue: int = 10_000,
                 journal_path: Optional[str] = None,
                 journal_fsync: bool = True,
                 transport: str = "threaded",
                 shed_retry_after: float = 1.0):
        if transport not in ("threaded", "async"):
            # validate BEFORE opening the journal: failing after would leak
            # the journal fd and leave a half-built object
            raise ValueError(f"unknown transport {transport!r} "
                             "(expected 'threaded' or 'async')")
        self.reply_timeout = reply_timeout
        #: Retry-After hint (seconds) sent with 429 shed responses
        self.shed_retry_after = shed_retry_after
        self._closed = False
        #: path prefix → fn(HTTPRequestData) -> HTTPResponseData. The
        #: telemetry endpoints are registered FIRST: _control_route matches
        #: prefixes in insertion order, so a later catch-all (e.g. the
        #: distributed forwarder's "/") cannot shadow /metrics or /healthz
        self.control_routes: Dict[str, object] = {
            "/healthz": self._healthz_route,
            "/metrics": self._metrics_route,
            "/debug/traces": self._debug_traces_route,
            "/debug/slo": self._debug_slo_route,
            "/debug/costs": self._debug_costs_route,
            "/debug/scenario": self._debug_scenario_route,
            "/debug/timeseries": self._debug_timeseries_route,
            "/debug/profile": self._debug_profile_route,
            "/debug/registry": self._debug_registry_route,
            "/models": self._models_route,
        }
        #: guards the single on-demand profiler capture slot
        self._profile_lock = threading.Lock()
        self._profile_active: Optional[dict] = None
        self._profile_thread: Optional[threading.Thread] = None
        #: request_id → CachedRequest (reference: routingTable ``:689``)
        self._routing: Dict[str, CachedRequest] = {}
        #: epoch → {request_id: CachedRequest} (reference: historyQueues)
        self._history: Dict[int, Dict[str, CachedRequest]] = {}
        self._epoch = 0
        self._lock = threading.Lock()
        #: durable epoch/request journal (the HTTPOffset role,
        #: ``HTTPSourceV2.scala:96-113``) — survives PROCESS death
        self._journal = None
        pending = {}
        #: live decode sessions rehydrated from the journal at construction
        #: — a restarted worker hands these to its engine via
        #: ``ContinuousDecoder.restore_session`` (cold path; the pages died
        #: with the previous process)
        self.replayed_sessions: Dict[str, dict] = {}
        if journal_path is not None:
            from .journal import ServingJournal
            self._journal = ServingJournal(journal_path, fsync=journal_fsync)
            self._epoch, pending = self._journal.replay()
            self.replayed_sessions = self._journal.replay_sessions()
        # the queue must hold every rehydrated request up front (no consumer
        # exists yet) — a journal larger than max_queue must not deadlock
        # the constructor. Tenant weights come live from the process-global
        # model registry, so /models tenant edits apply without a restart.
        self._queue: AdmissionQueue = AdmissionQueue(
            max(max_queue, len(pending)),
            weight_fn=lambda t: _get_model_registry().tenant_weight(t))
        for rid, (epoch, request) in pending.items():
            cached = CachedRequest(rid, epoch, request, replayed=True)
            self._routing[rid] = cached
            self._history.setdefault(epoch, {})[rid] = cached
            # unconditional append: rehydrated requests were admitted in a
            # previous life — tenant budgets must not drop them now
            self._queue.put(cached)
        self.host = host
        self.api_path = api_path
        try:
            if transport == "async":
                self._httpd = None
                self._aio: Optional[_AsyncHTTPServer] = _AsyncHTTPServer(
                    self, host, port)
                self.port = self._aio.port
            elif transport == "threaded":
                self._aio = None
                self._httpd = ThreadingHTTPServer((host, port), _Handler)
                # keep-alive handler threads must not block process exit
                self._httpd.daemon_threads = True
                self._httpd.worker_server = self  # type: ignore[attr-defined]
                self.port = self._httpd.server_address[1]
                self._thread = threading.Thread(
                    target=self._httpd.serve_forever,
                    name=f"serving-{self.port}", daemon=True)
                self._thread.start()
        except BaseException:
            # transport startup failed (e.g. EADDRINUSE) after the journal
            # was opened — close it so the half-built object leaks no fd
            if self._journal is not None:
                self._journal.close()
            raise
        # callback gauges, sampled at scrape/snapshot time (zero hot-path
        # cost); labeled by port so concurrent servers don't collide —
        # close() drops the series
        _M_QUEUE_DEPTH.set_function(self._queue.qsize, port=str(self.port))
        _M_INFLIGHT.set_function(self.pending_count, port=str(self.port))
        # idempotent: (re)stamps mmlspark_build_info so any scraped server
        # exposes version/jax/backend even after a registry reset in tests;
        # HBM gauges only register when jax is already initialized (neither
        # triggers a backend import)
        _build_info()
        _register_hbm_gauges()
        # time-series plane (observability/timeseries.py): the registry
        # sampler is process-global and refcounted — however many servers
        # a test process runs, one scrape thread feeds one store; close()
        # releases it. The per-port sources feed the queue-saturation
        # alert and the drain-rate history suggest_retry_after seeds its
        # EWMA from after an idle gap (history_key ties the queue to its
        # labeled series).
        self._ts_sampler = _acquire_sampler()
        self._ts_sampler.add_source(
            "mmlspark_queue_saturation", self._queue_saturation,
            port=str(self.port))
        self._ts_sampler.add_source(
            "mmlspark_queue_drain_rate",
            lambda: self._queue.drain_rate() or None, port=str(self.port))
        self._queue.history_key = str(self.port)

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}{self.api_path}"

    def _control_route(self, path: str):
        for prefix, fn in self.control_routes.items():
            if path.startswith(prefix):
                return fn
        return None

    # -- telemetry ----------------------------------------------------------
    def _observe_request(self, transport: str, method: Optional[str],
                         code: int, seconds: Optional[float],
                         path: Optional[str] = None,
                         trace_span: Optional[object] = None) -> None:
        # "/_"-prefixed paths are internal cross-worker hops (/_reply,
        # /_forward) — counting them would double-bill one logical request
        # across workers; only the OWNING worker's user-facing answer counts
        if path is not None and path.startswith("/_"):
            return
        _M_REQUESTS.inc(transport=transport, method=method or "?",
                        code=str(code))
        tenant = "default"
        model = "default"
        if trace_span is not None:
            attrs = getattr(trace_span, "attrs", {})
            tenant = attrs.get("tenant", "default")
            # registry-resolved requests carry "name@version" — the SLO
            # class dimension check_canaries() compares windows over
            model = attrs.get("model", "default")
        # same admission rule as requests_total, so the per-class SLO
        # scorecard totals reconcile against that counter exactly
        _get_tracker().observe(transport=transport,
                               route=_classify_route(path),
                               model=model,
                               seconds=seconds, error=code >= 500,
                               tenant=tenant)
        if seconds is not None:
            # under an active span the histogram captures the trace_id as
            # an OpenMetrics exemplar (when tracing.set_exemplars is on)
            with _tracing.activate(trace_span):
                _M_REQ_LATENCY.observe(seconds, transport=transport)

    #: a watchdog stall younger than this marks /healthz degraded
    STALL_DEGRADED_SECONDS = 60.0

    def _degraded_reasons(self) -> List[str]:
        """Soft-failure signals for /healthz. Degraded is advisory — the
        response stays HTTP 200 so load balancers keep the worker in
        rotation while operators (and the e2e suite) see WHY it is
        struggling: open circuits to peers, a nearly-full admission queue,
        or a recent device-stall verdict from the watchdog."""
        reasons = []
        for peer in _open_breakers():
            reasons.append(f"breaker_open:{peer}")
        maxsize = self._queue.maxsize
        if maxsize > 0 and self._queue.qsize() >= 0.8 * maxsize:
            reasons.append(
                f"queue_pressure:{self._queue.qsize()}/{maxsize}")
        age = _get_watchdog().last_stall_age()
        if age is not None and age <= self.STALL_DEGRADED_SECONDS:
            reasons.append(f"watchdog_stall:{round(age, 1)}s_ago")
        # sustained-signal alerts (observability/timeseries.py): a rule in
        # its firing state names itself here until it resolves — one bad
        # sample never degrades health, the hysteresis window must hold
        for rule in _get_alert_engine().firing():
            reasons.append(f"alert_firing:{rule}")
        return reasons

    def _queue_saturation(self) -> float:
        """Admission-queue fill fraction, sampled into the store per tick
        (the default queue-saturation alert reads this series)."""
        maxsize = self._queue.maxsize
        return self._queue.qsize() / maxsize if maxsize > 0 else 0.0

    def _hbm_bytes_in_use(self) -> Optional[float]:
        """Summed ``mmlspark_device_hbm_bytes_in_use`` across devices, or
        None before the watchdog's HBM gauges register (jax not yet
        initialized). Rides the health digest because worker_snapshot()
        federates counters and histograms only — a gauge would never
        reach the driver's cluster series otherwise."""
        rows = _M_HBM_IN_USE.series()
        if not rows:
            return None
        total = 0.0
        for _labels, series in rows:
            try:
                total += float(series.get())
            except Exception:
                return None
        return total

    def health_digest(self) -> Dict[str, object]:
        """Compact health fields the distributed heartbeat piggybacks to
        the driver registry (serving/distributed.py): queue depth,
        in-flight count, open breakers, and the age of the last watchdog
        stall — enough for ``GET /workers`` to show WHY a worker is
        struggling without another per-worker scrape."""
        age = _get_watchdog().last_stall_age()
        return {"queue_depth": self._queue.qsize(),
                "in_flight": self.pending_count(),
                "open_breakers": sorted(_open_breakers()),
                "stall_age_seconds": None if age is None else round(age, 3),
                "hbm_bytes_in_use": self._hbm_bytes_in_use(),
                "degraded": bool(self._degraded_reasons()),
                # federated registry/admission state: which versions this
                # worker serves (live/canary per model) and its per-tenant
                # backlog — GET /workers shows rollout + fairness posture
                # cluster-wide without per-worker scrapes
                "registry": _get_model_registry().digest(),
                "admission": self._queue.snapshot(),
                # durability posture: journal size, live (recoverable)
                # sessions, per-type record counts — the fields the driver
                # needs to decide whether a dead worker's sessions are
                # worth a cold reassignment sweep
                "journal": (self._journal.digest()
                            if self._journal is not None else None)}

    def _healthz_route(self, request: HTTPRequestData) -> HTTPResponseData:
        import json as _json
        with self._lock:
            pending = len(self._routing)
            epoch = self._epoch
        reasons = self._degraded_reasons()
        body = {"status": "degraded" if reasons else "ok",
                "reasons": reasons,
                "transport": "async" if self._aio is not None else "threaded",
                "port": self.port,
                "queued": self._queue.qsize(),
                "pending": pending,
                "epoch": epoch,
                "uptime_seconds": round(_process_uptime(), 3)}
        return HTTPResponseData(
            headers=[HeaderData("Content-Type", "application/json")],
            entity=EntityData.from_string(_json.dumps(body)),
            status_line=StatusLineData(status_code=200))

    def _metrics_route(self, request: HTTPRequestData) -> HTTPResponseData:
        # Content-Type must ride in resp.headers — the transports render
        # those, not the entity's content_type field
        return HTTPResponseData(
            headers=[HeaderData("Content-Type", _PROM_CONTENT_TYPE)],
            entity=EntityData.from_string(_render_metrics(),
                                          content_type=_PROM_CONTENT_TYPE),
            status_line=StatusLineData(status_code=200))

    def _debug_traces_route(self, request: HTTPRequestData
                            ) -> HTTPResponseData:
        """Flight-recorder browser. ``GET /debug/traces`` lists summaries
        (newest first, slow-kept traces ahead of the ring);
        ``GET /debug/traces/{trace_id}`` returns one full span tree, or
        Chrome-trace JSON with ``?format=chrome`` (loadable in
        chrome://tracing / Perfetto, same shape SpanTracer.export writes).

        Registered in ``control_routes`` ahead of any catch-all (the
        distributed forwarder appends "/" LAST), so it stays reachable on
        every worker."""
        import json as _json

        def _resp(payload: object, status: int = 200) -> HTTPResponseData:
            return HTTPResponseData(
                headers=[HeaderData("Content-Type", "application/json")],
                entity=EntityData.from_string(_json.dumps(payload)),
                status_line=StatusLineData(status_code=status))

        recorder = _tracing.get_flight_recorder()
        path, _, query = request.url.partition("?")
        trace_id = path[len("/debug/traces"):].strip("/")
        if not trace_id:
            return _resp({"slow_threshold_seconds": recorder.slow_threshold,
                          "traces": recorder.summaries()})
        trace = recorder.get(trace_id)
        if trace is None:
            return _resp({"error": "unknown trace_id",
                          "trace_id": trace_id}, status=404)
        if "format=chrome" in query:
            return _resp(trace.to_chrome())
        return _resp(trace.to_dict())

    def _debug_slo_route(self, request: HTTPRequestData) -> HTTPResponseData:
        """``GET /debug/slo`` — the rolling SLO scorecard for every
        workload class this process has served, plus the policy verdicts
        (p99 objective, availability, error-budget burn rate).

        Each successful render is also harvested into the tuning
        :class:`~mmlspark_tpu.tuning.observations.ObservationStore` as
        ``source="slo_scorecard"`` rows (skip with ``?harvest=0``), so
        the cost model sees quality alongside throughput."""
        import json as _json
        _, _, query = request.url.partition("?")
        card = _get_tracker().scorecard()
        if "harvest=0" not in query:
            # lazy: tuning imports observability; importing it the other
            # way at module scope would be a cycle
            from ..tuning.observations import harvest_scorecard
            card["harvested"] = harvest_scorecard(card)
        return HTTPResponseData(
            headers=[HeaderData("Content-Type", "application/json")],
            entity=EntityData.from_string(_json.dumps(card)),
            status_line=StatusLineData(status_code=200))

    def _debug_scenario_route(self, request: HTTPRequestData
                              ) -> HTTPResponseData:
        """``GET /debug/scenario`` — live progress of the scenario the
        loadgen harness is currently driving (sent/done/ok/shed/error
        counts and, once finished, the scorecard summary). Registered in
        ``control_routes``, so it serves on both transports; idle state
        when no scenario has ever run in this process."""
        import json as _json
        # lazy import: loadgen is a *client* of the serving plane — the
        # server must not require it at construction time
        from ..loadgen.progress import get_progress
        return HTTPResponseData(
            headers=[HeaderData("Content-Type", "application/json")],
            entity=EntityData.from_string(
                _json.dumps(get_progress().snapshot())),
            status_line=StatusLineData(status_code=200))

    def _debug_timeseries_route(self, request: HTTPRequestData
                                ) -> HTTPResponseData:
        """``GET /debug/timeseries`` — the process-global metric history
        (observability/timeseries.py): per-series downsampled windows plus
        the alert engine's rule state. Registered in ``control_routes``,
        so it serves on both transports.

        Query params: ``seconds`` (trailing window, default 120),
        ``series`` (comma-separated name filter), and ``format=text`` for
        the terminal sparkline triage view."""
        import json as _json
        _, _, query = request.url.partition("?")
        params: Dict[str, str] = {}
        for part in query.split("&"):
            key, _, value = part.partition("=")
            if key:
                params[key] = value
        try:
            seconds = float(params.get("seconds", "120"))
        except ValueError:
            seconds = 120.0
        names = ([n for n in params["series"].split(",") if n]
                 if params.get("series") else None)
        store = _get_ts_store()
        if params.get("format") == "text":
            return HTTPResponseData(
                headers=[HeaderData("Content-Type",
                                    "text/plain; charset=utf-8")],
                entity=EntityData.from_string(
                    _render_sparklines(store, seconds, names=names)),
                status_line=StatusLineData(status_code=200))
        engine = _get_alert_engine()
        payload = store.snapshot(seconds, names=names)
        payload["alerts"] = engine.state()
        payload["firing"] = engine.firing()
        return HTTPResponseData(
            headers=[HeaderData("Content-Type", "application/json")],
            entity=EntityData.from_string(_json.dumps(payload)),
            status_line=StatusLineData(status_code=200))

    def _debug_costs_route(self, request: HTTPRequestData
                           ) -> HTTPResponseData:
        """``GET /debug/costs`` — the cost ledger's per-class resource
        totals and the top-K heavy-hitter table (each entry joinable to
        ``/debug/traces/{trace_id}``).

        Each successful render is also harvested into the tuning
        :class:`~mmlspark_tpu.tuning.observations.ObservationStore` as
        ``source="cost_ledger"`` rows (skip with ``?harvest=0``), so the
        cost model sees attributed cost alongside throughput and SLO
        facts."""
        import json as _json
        _, _, query = request.url.partition("?")
        snap = _get_ledger().snapshot()
        if "harvest=0" not in query:
            # lazy import — tuning imports observability (see /debug/slo)
            from ..tuning.observations import harvest_costs
            snap["harvested"] = harvest_costs(snap)
        return HTTPResponseData(
            headers=[HeaderData("Content-Type", "application/json")],
            entity=EntityData.from_string(_json.dumps(snap)),
            status_line=StatusLineData(status_code=200))

    #: on-demand profiler capture length ceiling (seconds)
    MAX_PROFILE_SECONDS = 60.0

    def _debug_profile_route(self, request: HTTPRequestData
                             ) -> HTTPResponseData:
        """``GET /debug/profile?seconds=N`` — capture an on-demand
        ``jax.profiler`` device trace for N seconds (default 3, capped at
        :data:`MAX_PROFILE_SECONDS`) into a fresh directory under the
        watchdog's diagnostic dir, without restarting the worker.

        The capture runs on a background thread so neither transport's
        accept path blocks for N seconds; the response returns
        immediately with the log dir to point TensorBoard at. One capture
        at a time: a second request while one is running gets 409."""
        import json as _json

        def _resp(payload: object, status: int = 200) -> HTTPResponseData:
            return HTTPResponseData(
                headers=[HeaderData("Content-Type", "application/json")],
                entity=EntityData.from_string(_json.dumps(payload)),
                status_line=StatusLineData(status_code=status))

        _, _, query = request.url.partition("?")
        seconds = 3.0
        for part in query.split("&"):
            if part.startswith("seconds="):
                try:
                    seconds = float(part[len("seconds="):])
                except ValueError:
                    return _resp({"error": "bad seconds value"}, status=400)
        seconds = min(max(seconds, 0.05), self.MAX_PROFILE_SECONDS)
        wd = _get_watchdog()
        log_dir = os.path.join(
            wd.diag_dir(), f"profile_{self.port}_{int(time.time())}")
        with self._profile_lock:
            if self._profile_active is not None:
                return _resp({"error": "profile capture already active",
                              **self._profile_active}, status=409)
            self._profile_active = {"log_dir": log_dir, "seconds": seconds}

        def _capture() -> None:
            # tracked so close() can wait for an in-flight capture: tearing
            # the process down mid-stop_trace crashes inside the profiler
            from ..utils import profiling as _profiling
            try:
                with _profiling.trace(log_dir):
                    time.sleep(seconds)
                _log_event("profile_captured", log_dir=log_dir,
                           seconds=seconds, port=self.port)
            except Exception as exc:
                # profiler unavailable (no jax backend, capture collision)
                # — the endpoint must never take the worker down
                _log_event("profile_failed", level=logging.WARNING,
                           log_dir=log_dir, error=repr(exc))
            finally:
                with self._profile_lock:
                    self._profile_active = None

        os.makedirs(log_dir, exist_ok=True)
        t = threading.Thread(target=_capture, name="mmlspark-profile",
                             daemon=True)
        self._profile_thread = t
        t.start()
        return _resp({"started": True, "log_dir": log_dir,
                      "seconds": seconds})

    def _models_route(self, request: HTTPRequestData) -> HTTPResponseData:
        """``GET /models`` — registry snapshot; ``POST /models`` — admin
        actions (load/promote/rollback/retire/tenant/check) as a JSON
        body. Registered on both transports via control_routes. HTTP
        loads are declarative (no in-process handle/warm_up — engines
        register those directly via ``get_registry().load``)."""
        import json as _json

        def _resp(payload: object, status: int = 200) -> HTTPResponseData:
            return HTTPResponseData(
                entity=EntityData.from_string(_json.dumps(payload)),
                status_line=StatusLineData(status_code=status))

        registry = _get_model_registry()
        if (request.method or "GET").upper() != "POST":
            return _resp(registry.snapshot())
        try:
            req_body = (_json.loads(request.entity.string_content())
                        if request.entity else {})
        except ValueError:
            return _resp({"error": "invalid JSON body"}, 400)
        action = str(req_body.get("action", "")).lower()
        try:
            if action == "load":
                mv = registry.load(
                    req_body["name"], req_body["version"],
                    canary_percent=float(req_body.get("canary_percent",
                                                      0.0)),
                    shadow_percent=float(req_body.get("shadow_percent",
                                                      0.0)),
                    block=bool(req_body.get("block", True)))
                return _resp({"loaded": mv.snapshot()})
            if action == "promote":
                mv = registry.promote(req_body["name"], req_body["version"])
                return _resp({"promoted": mv.snapshot()})
            if action == "rollback":
                mv = registry.rollback(req_body["name"],
                                       req_body.get("version"),
                                       reason=str(req_body.get(
                                           "reason", "manual")))
                return _resp({"rolled_back":
                              mv.snapshot() if mv else None})
            if action in ("retire", "unload"):
                out = registry.retire(
                    req_body["name"], req_body["version"],
                    drain_timeout=float(req_body.get("drain_timeout",
                                                     5.0)))
                return _resp(out)
            if action == "tenant":
                registry.set_tenant(req_body["tenant"],
                                    float(req_body["weight"]))
                return _resp({"tenants": registry.tenants()})
            if action == "check":
                return _resp({"verdicts": registry.check_canaries()})
        except KeyError as exc:
            return _resp({"error": f"missing field: {exc}"}, 400)
        except ValueError as exc:
            return _resp({"error": str(exc)}, 400)
        return _resp({"error": f"unknown action {action!r}"}, 400)

    def _debug_registry_route(self, request: HTTPRequestData
                              ) -> HTTPResponseData:
        """``GET /debug/registry`` — full rollout state plus this
        worker's admission (WFQ) snapshot: version states, canary
        verdicts, shadow diffs, tenant weights and backlogs."""
        import json as _json
        registry = _get_model_registry()
        payload = {"registry": registry.snapshot(),
                   "canary_verdicts": registry.check_canaries(),
                   "admission": self._queue.snapshot()}
        return HTTPResponseData(
            entity=EntityData.from_string(_json.dumps(payload)),
            status_line=StatusLineData(status_code=200))

    # -- ingest -------------------------------------------------------------
    def _shed(self, tenant: str = "default", reason: str = "queue_full",
              exc: Optional[BaseException] = None) -> Overloaded:
        _M_SHED.inc()
        _get_tracker().shed(
            transport="async" if self._aio is not None else "threaded",
            route="api", tenant=tenant)
        # load-aware Retry-After: backlog over the measured drain rate,
        # scaled up for a tenant shed over its weighted budget; the
        # shed_retry_after knob survives as the floor
        retry_after = self._queue.suggest_retry_after(
            floor=self.shed_retry_after,
            tenant=tenant if isinstance(exc, TenantOverBudget) else None)
        _log_event("request_shed", port=self.port,
                   queued=self._queue.qsize(), tenant=tenant,
                   reason=reason, retry_after=retry_after)
        return Overloaded(retry_after)

    def _enqueue(self, request: HTTPRequestData) -> CachedRequest:
        # headers FIRST: the tenant decides which admission budget applies
        # and the model header decides which registry version serves
        traceparent = deadline = None
        tenant = "default"
        model_name = None
        for h in request.headers:
            name = h.name.lower()
            if name == "traceparent":
                traceparent = h.value
            elif name == "x-mmlspark-deadline":
                deadline = Deadline.from_header(h.value)
            elif name == "x-mmlspark-tenant":
                # free-form header, but cardinality-safe: the SLO tracker
                # and cost ledger both collapse classes beyond MAX_CLASSES
                # into "other", so a tenant burst cannot blow up labels
                tenant = h.value.strip() or "default"
            elif name == "x-mmlspark-model":
                model_name = h.value.strip() or None
        # admission control BEFORE any span/journal/routing work is spent
        # on a request we won't park: global full sheds everyone, tenant
        # budget sheds the over-budget tenant first (raises Overloaded →
        # the transports answer 429 + Retry-After)
        try:
            self._queue.check_admit(tenant)
        except TenantOverBudget as exc:
            raise self._shed(tenant, reason="tenant_budget",
                             exc=exc) from None
        except queue.Full as exc:
            raise self._shed(tenant, reason="queue_full", exc=exc) from None
        injector = _get_injector()
        if injector.enabled:
            injector.fire("enqueue")
        # ONE root span per logical request, minted at the single point
        # every ingest shape funnels through — both transports AND the
        # distributed forwarder (whose hop carries the original traceparent,
        # so the forwarded leg continues the same trace)
        request_id = _tracing.new_request_id()
        registry = _get_model_registry()
        resolution = None
        span_extra = {}
        if model_name is not None:
            # canary/shadow split happens HERE, at ingest: the resolved
            # "name@version" rides the root span's model attr, so SLO
            # windows and ledger classes separate candidate from incumbent
            resolution = registry.resolve(model_name, request_id)
            span_extra["model"] = resolution.label
        root = _tracing.start_trace(
            "server.request", traceparent=traceparent,
            request_id=request_id, method=request.method, url=request.url,
            route=_classify_route(request.url), tenant=tenant,
            transport="async" if self._aio is not None else "threaded",
            **span_extra)
        with self._lock:
            cached = CachedRequest(
                request_id, self._epoch, request, trace_span=root,
                deadline=deadline, tenant=tenant,
                model_label=resolution.label if resolution else None)
        # write-ahead, BEFORE the routing-table insert: a failed append
        # (disk full, journal closed mid-shutdown) must error this request
        # out cleanly instead of leaking a never-queued routing entry that
        # pins its epoch's history forever
        if self._journal is not None:
            self._journal.record_request(cached.request_id, cached.epoch,
                                         request, trace_id=root.trace_id)
        with self._lock:
            self._routing[cached.request_id] = cached
            self._history.setdefault(cached.epoch, {})[cached.request_id] = cached
        try:
            self._queue.put_nowait(cached)
        except queue.Full as exc:
            # lost the admission race — undo the bookkeeping above so the
            # shed request leaks no routing entry and won't rehydrate
            with self._lock:
                self._routing.pop(cached.request_id, None)
                self._history.get(cached.epoch, {}).pop(cached.request_id,
                                                        None)
            if self._journal is not None:
                self._journal.record_reply(cached.request_id)
            if resolution is not None:
                registry.note_done(resolution.label)
                if resolution.shadow is not None:
                    registry.note_done(resolution.shadow)
            root.end(status=429)
            reason = ("tenant_budget" if isinstance(exc, TenantOverBudget)
                      else "queue_full")
            raise self._shed(tenant, reason=reason, exc=exc) from None
        if resolution is not None and resolution.shadow is not None:
            self._mirror_shadow(cached, resolution.shadow)
        return cached

    def _mirror_shadow(self, primary: CachedRequest,
                       shadow_label: str) -> None:
        """Mirror an admitted request to the shadow (candidate) version: a
        synthetic CachedRequest that flows through the normal queue/engine
        path but is never journaled and never answers a caller — both
        replies land in the registry's shadow join, which diffs them."""
        registry = _get_model_registry()
        shadow_id = _tracing.new_request_id()
        cached = CachedRequest(shadow_id, primary.epoch, primary.request,
                               tenant=primary.tenant,
                               model_label=shadow_label, shadow=True)
        with self._lock:
            self._routing[shadow_id] = cached
            self._history.setdefault(cached.epoch, {})[shadow_id] = cached
        try:
            # best-effort: a full queue drops the mirror, never the primary
            self._queue.put_nowait(cached)
        except queue.Full:
            with self._lock:
                self._routing.pop(shadow_id, None)
                self._history.get(cached.epoch, {}).pop(shadow_id, None)
            registry.note_done(shadow_label)
            return
        trace_id = (primary.trace_span.trace.trace_id
                    if primary.trace_span is not None else None)
        registry.shadow_begin(primary.request_id, shadow_id, shadow_label,
                              trace_id=trace_id)
        primary.add_done_callback(
            lambda resp: registry.shadow_result(
                primary.request_id, _entity_bytes(resp), from_shadow=False))
        cached.add_done_callback(
            lambda resp: registry.shadow_result(
                primary.request_id, _entity_bytes(resp), from_shadow=True))

    def wait_budget(self, cached: CachedRequest) -> float:
        """How long a transport may park this request: ``reply_timeout``,
        clamped to the request's propagated deadline when it carries one."""
        if cached.deadline is None:
            return self.reply_timeout
        return max(0.0, cached.deadline.cap(self.reply_timeout))

    # -- engine side --------------------------------------------------------
    def get_batch(self, max_rows: int, timeout: float = 0.1):
        """Drain up to ``max_rows`` parked requests (blocks up to ``timeout``
        for the first one). Returns a list of :class:`CachedRequest`."""
        out = []
        try:
            out.append(self._queue.get(timeout=timeout))
        except queue.Empty:
            return out
        while len(out) < max_rows:
            try:
                out.append(self._queue.get_nowait())
            except queue.Empty:
                break
        self._charge_queue_wait(out)
        return out

    def _charge_queue_wait(self, batch) -> None:
        """Bill each dequeued request's park time to its own workload
        class — the cost-ledger charge site for queue_wait_seconds."""
        ledger = _get_ledger()
        now = time.monotonic()
        for cached in batch:
            span = cached.trace_span
            cls = tid = None
            if span is not None:
                attrs = span.attrs
                cls = (str(attrs.get("transport", "untraced")),
                       str(attrs.get("route", "api")),
                       str(attrs.get("model", "default")),
                       str(attrs.get("tenant", "default")))
                tid = span.trace.trace_id
            ledger.charge("queue_wait_seconds",
                          now - cached.enqueued_at, cls=cls, trace_id=tid)

    def _take_answered(self, request_id: str) -> Optional[CachedRequest]:
        """Pop a parked request and mark it answered (routing table,
        epoch history, journal reply record) — THE bookkeeping sequence
        for every reply shape, one-shot or streaming."""
        with self._lock:
            cached = self._routing.pop(request_id, None)
            if cached is not None:
                self._history.get(cached.epoch, {}).pop(request_id, None)
        if cached is not None:
            if cached.model_label is not None:
                # in-flight accounting: retire()'s drain barrier unblocks
                # once every resolved request of a version has answered
                _get_model_registry().note_done(cached.model_label)
            # shadow mirrors were never journaled as requests — recording
            # a reply for them would orphan the journal's pairing
            if self._journal is not None and not cached.shadow:
                self._journal.record_reply(request_id)
        return cached

    def trace_span(self, request_id: str):
        """Root span of a still-parked request (None when unknown/answered
        or untraced) — the engine activates it to attach batch spans."""
        with self._lock:
            cached = self._routing.get(request_id)
        return cached.trace_span if cached is not None else None

    def model_label(self, request_id: str) -> Optional[str]:
        """Resolved ``name@version`` of a still-parked request (None when
        unknown or unversioned) — serving engines group a drained batch
        by it to dispatch each row to its version's handle."""
        with self._lock:
            cached = self._routing.get(request_id)
        return cached.model_label if cached is not None else None

    def reply(self, request_id: str, response: HTTPResponseData) -> bool:
        """Route a response to the parked connection
        (parity: ``replyTo`` ``:536-554``)."""
        cached = self._take_answered(request_id)
        if cached is None:
            return False
        if cached.trace_span is not None:
            # idempotent close (False if the transport 504'd it already);
            # ending the root hands the trace to the flight recorder
            cached.trace_span.end(
                status=response.status_line.status_code)
        cached.respond(response)
        return True

    def reply_json(self, request_id: str, payload, status: int = 200) -> bool:
        import json as _json
        ent = EntityData.from_string(_json.dumps(payload))
        return self.reply(request_id, HTTPResponseData(
            entity=ent, status_line=StatusLineData(status_code=status)))

    def reply_stream(self, request_id: str,
                     content_type: str = "text/event-stream"
                     ) -> Optional[StreamingReply]:
        """Open an incremental (SSE) reply for a parked request; returns
        the handle to ``send``/``send_event``/``close`` on, or None when
        the request is unknown/already answered. The request is marked
        answered when the stream OPENS (stream content is not journaled —
        at-most-once, like the reply record itself)."""
        cached = self._take_answered(request_id)
        if cached is None:
            return None
        if cached.trace_span is not None:
            # the trace covers accept → stream OPEN (chunk timing belongs
            # to the stream itself, which may outlive the span tree)
            cached.trace_span.end(status=200, streaming=True)
        stream = StreamingReply(content_type)
        cached.respond(stream)
        return stream

    # -- epoch / replay -----------------------------------------------------
    def commit_epoch(self) -> int:
        """Close the current epoch; fully-answered epochs drop their history
        (parity: ``commit`` ``:609-645``)."""
        with self._lock:
            done = [e for e, reqs in self._history.items()
                    if e < self._epoch and not reqs]
            for e in done:
                del self._history[e]
            self._epoch += 1
            epoch = self._epoch
        if self._journal is not None:
            self._journal.record_epoch(epoch)
            self._journal.maybe_compact(epoch)
        return epoch

    def replay_unanswered(self) -> int:
        """Re-enqueue every routed-but-unanswered request — the recovery a
        restarted reader performs (parity: ``registerPartition`` rehydration
        ``:489-506``). Returns the number of requests replayed."""
        # drain the live queue BEFORE snapshotting: a request that arrives
        # between snapshot and drain would otherwise be drained but absent
        # from the snapshot, and so lost
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        with self._lock:
            pending = [c for c in self._routing.values() if not c._done.is_set()]
        for c in pending:
            self._queue.put(c)
        return len(pending)

    def pending_count(self) -> int:
        with self._lock:
            return len(self._routing)

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        self._closed = True
        t = self._profile_thread
        if t is not None and t.is_alive():
            # bound the wait: a capture is at most MAX_PROFILE_SECONDS of
            # sleep plus stop_trace; a wedged profiler must not wedge close
            t.join(timeout=self.MAX_PROFILE_SECONDS + 10.0)
        _M_QUEUE_DEPTH.remove(port=str(self.port))
        _M_INFLIGHT.remove(port=str(self.port))
        # drop this port's sampler sources, then release the refcounted
        # sampler (the scrape thread stops with the last server); None'd
        # so a double close() cannot over-release
        if self._ts_sampler is not None:
            self._ts_sampler.remove_source("mmlspark_queue_saturation",
                                           port=str(self.port))
            self._ts_sampler.remove_source("mmlspark_queue_drain_rate",
                                           port=str(self.port))
            self._ts_sampler = None
            _release_sampler()
        if self._aio is not None:
            self._aio.close()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._thread.join(timeout=5)
        if self._journal is not None:
            self._journal.close()
