"""Per-worker HTTP server with epoch-keyed queues and replay.

Parity: ``WorkerServer`` (``HTTPSourceV2.scala:476-697``) — a lightweight
HTTP server per worker process; incoming requests are parked in an
epoch-keyed queue (``:512-518``), handed to the engine as batches, and
answered later through a routing table (``replyTo``/``respondToHTTPExchange``,
``:536-554``). Unanswered requests of an epoch survive an engine restart and
are re-served (history rehydration, ``:489-506,556-568``).

Implementation: ``ThreadingHTTPServer`` (one thread per connection, parked on
a per-request ``threading.Event`` until the reply lands) — the Python shape
of the reference's ``com.sun.net.httpserver`` + blocked ``HttpExchange``.
"""

from __future__ import annotations

import queue
import threading
import uuid
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from ..io.http.schema import (EntityData, HeaderData, HTTPRequestData,
                              HTTPResponseData, StatusLineData)

__all__ = ["CachedRequest", "WorkerServer"]


@dataclass
class CachedRequest:
    """Parity: ``CachedRequest`` — a parked exchange + its id."""
    request_id: str
    epoch: int
    request: HTTPRequestData
    #: True when rehydrated from the journal after a process restart — the
    #: original connection is gone; the reply is journaled, not delivered
    replayed: bool = False
    _done: threading.Event = field(default_factory=threading.Event, repr=False)
    _response: Optional[HTTPResponseData] = field(default=None, repr=False)

    def respond(self, response: HTTPResponseData) -> None:
        self._response = response
        self._done.set()

    def wait(self, timeout: Optional[float]) -> Optional[HTTPResponseData]:
        if self._done.wait(timeout):
            return self._response
        return None


class _Handler(BaseHTTPRequestHandler):
    server_version = "mmlspark-tpu-serving/1.0"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet
        pass

    def _read_body(self) -> bytes:
        te = (self.headers.get("Transfer-Encoding") or "").lower()
        if "chunked" in te:
            # drain chunked framing; leaving it unread would corrupt the
            # keep-alive connection for the next pipelined request
            chunks = []
            while True:
                size_line = self.rfile.readline(65536).strip()
                size = int(size_line.split(b";")[0] or b"0", 16)
                if size == 0:
                    while self.rfile.readline(65536) not in (b"\r\n", b"\n", b""):
                        pass  # trailers
                    break
                chunks.append(self.rfile.read(size))
                self.rfile.read(2)  # CRLF after each chunk
            return b"".join(chunks)
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length) if length else b""

    def _handle(self):
        ws: "WorkerServer" = self.server.worker_server  # type: ignore[attr-defined]
        try:
            body = self._read_body()
        except (ValueError, ConnectionError):
            self.send_response(400, "bad request body")
            self.send_header("Content-Length", "0")
            self.end_headers()
            self.close_connection = True
            return
        req = HTTPRequestData(
            url=self.path, method=self.command,
            headers=[HeaderData(k, v) for k, v in self.headers.items()],
            entity=EntityData(content=body, content_length=len(body)) if body else None)
        # control routes (internal cross-worker endpoints: reply forwarding,
        # request forwarding) answer synchronously, bypassing the queue
        ctrl = ws._control_route(self.path)
        if ctrl is not None:
            try:
                resp = ctrl(req)
            except Exception as e:  # control failures must not park forever
                resp = HTTPResponseData(
                    entity=EntityData.from_string(str(e)),
                    status_line=StatusLineData(status_code=500))
        else:
            cached = ws._enqueue(req)
            resp = cached.wait(ws.reply_timeout)
        if resp is None:
            self.send_response(504, "serving reply timeout")
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        payload = resp.entity.content if resp.entity else b""
        self.send_response(resp.status_line.status_code,
                           resp.status_line.reason_phrase or None)
        sent = {h.name.lower() for h in resp.headers}
        for h in resp.headers:
            if h.name.lower() not in ("content-length", "connection"):
                self.send_header(h.name, h.value)
        if "content-type" not in sent and payload:
            self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        if payload:
            self.wfile.write(payload)

    do_GET = do_POST = do_PUT = do_DELETE = _handle


class WorkerServer:
    """HTTP listener + epoch request queue + reply routing table."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 api_path: str = "/", reply_timeout: float = 60.0,
                 max_queue: int = 10_000,
                 journal_path: Optional[str] = None,
                 journal_fsync: bool = True):
        self.reply_timeout = reply_timeout
        #: path prefix → fn(HTTPRequestData) -> HTTPResponseData
        self.control_routes: Dict[str, object] = {}
        #: request_id → CachedRequest (reference: routingTable ``:689``)
        self._routing: Dict[str, CachedRequest] = {}
        #: epoch → {request_id: CachedRequest} (reference: historyQueues)
        self._history: Dict[int, Dict[str, CachedRequest]] = {}
        self._epoch = 0
        self._lock = threading.Lock()
        #: durable epoch/request journal (the HTTPOffset role,
        #: ``HTTPSourceV2.scala:96-113``) — survives PROCESS death
        self._journal = None
        pending = {}
        if journal_path is not None:
            from .journal import ServingJournal
            self._journal = ServingJournal(journal_path, fsync=journal_fsync)
            self._epoch, pending = self._journal.replay()
        # the queue must hold every rehydrated request up front (no consumer
        # exists yet) — a journal larger than max_queue must not deadlock
        # the constructor
        self._queue: "queue.Queue[CachedRequest]" = queue.Queue(
            max(max_queue, len(pending)))
        for rid, (epoch, request) in pending.items():
            cached = CachedRequest(rid, epoch, request, replayed=True)
            self._routing[rid] = cached
            self._history.setdefault(epoch, {})[rid] = cached
            self._queue.put_nowait(cached)
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        # keep-alive handler threads must not block process exit
        self._httpd.daemon_threads = True
        self._httpd.worker_server = self  # type: ignore[attr-defined]
        self.host = host
        self.port = self._httpd.server_address[1]
        self.api_path = api_path
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name=f"serving-{self.port}", daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}{self.api_path}"

    def _control_route(self, path: str):
        for prefix, fn in self.control_routes.items():
            if path.startswith(prefix):
                return fn
        return None

    # -- ingest -------------------------------------------------------------
    def _enqueue(self, request: HTTPRequestData) -> CachedRequest:
        with self._lock:
            cached = CachedRequest(uuid.uuid4().hex, self._epoch, request)
        # write-ahead, BEFORE the routing-table insert: a failed append
        # (disk full, journal closed mid-shutdown) must error this request
        # out cleanly instead of leaking a never-queued routing entry that
        # pins its epoch's history forever
        if self._journal is not None:
            self._journal.record_request(cached.request_id, cached.epoch,
                                         request)
        with self._lock:
            self._routing[cached.request_id] = cached
            self._history.setdefault(cached.epoch, {})[cached.request_id] = cached
        self._queue.put(cached)
        return cached

    # -- engine side --------------------------------------------------------
    def get_batch(self, max_rows: int, timeout: float = 0.1):
        """Drain up to ``max_rows`` parked requests (blocks up to ``timeout``
        for the first one). Returns a list of :class:`CachedRequest`."""
        out = []
        try:
            out.append(self._queue.get(timeout=timeout))
        except queue.Empty:
            return out
        while len(out) < max_rows:
            try:
                out.append(self._queue.get_nowait())
            except queue.Empty:
                break
        return out

    def reply(self, request_id: str, response: HTTPResponseData) -> bool:
        """Route a response to the parked connection
        (parity: ``replyTo`` ``:536-554``)."""
        with self._lock:
            cached = self._routing.pop(request_id, None)
            if cached is not None:
                self._history.get(cached.epoch, {}).pop(request_id, None)
        if cached is None:
            return False
        if self._journal is not None:
            self._journal.record_reply(request_id)
        cached.respond(response)
        return True

    def reply_json(self, request_id: str, payload, status: int = 200) -> bool:
        import json as _json
        ent = EntityData.from_string(_json.dumps(payload))
        return self.reply(request_id, HTTPResponseData(
            entity=ent, status_line=StatusLineData(status_code=status)))

    # -- epoch / replay -----------------------------------------------------
    def commit_epoch(self) -> int:
        """Close the current epoch; fully-answered epochs drop their history
        (parity: ``commit`` ``:609-645``)."""
        with self._lock:
            done = [e for e, reqs in self._history.items()
                    if e < self._epoch and not reqs]
            for e in done:
                del self._history[e]
            self._epoch += 1
            epoch = self._epoch
        if self._journal is not None:
            self._journal.record_epoch(epoch)
            self._journal.maybe_compact(epoch)
        return epoch

    def replay_unanswered(self) -> int:
        """Re-enqueue every routed-but-unanswered request — the recovery a
        restarted reader performs (parity: ``registerPartition`` rehydration
        ``:489-506``). Returns the number of requests replayed."""
        # drain the live queue BEFORE snapshotting: a request that arrives
        # between snapshot and drain would otherwise be drained but absent
        # from the snapshot, and so lost
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        with self._lock:
            pending = [c for c in self._routing.values() if not c._done.is_set()]
        for c in pending:
            self._queue.put(c)
        return len(pending)

    def pending_count(self) -> int:
        with self._lock:
            return len(self._routing)

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._journal is not None:
            self._journal.close()
        self._thread.join(timeout=5)
