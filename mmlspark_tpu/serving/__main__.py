"""Serving entrypoint: ``python -m mmlspark_tpu.serving``.

Deployment surface for the docker/helm tooling (parity role: the reference's
serving containers under ``tools/helm``). Modes:

* ``--driver``: run the driver registry (one per cluster).
* default: run a worker. With ``--driver-url`` (or env
  ``MMLSPARK_TPU_DRIVER_URL``) the worker joins the distributed cluster
  (registration + heartbeat + cross-worker routing); without it, a
  standalone single-host WorkerServer.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import threading


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m mmlspark_tpu.serving")
    p.add_argument("--driver", action="store_true",
                   help="run the driver registry instead of a worker")
    p.add_argument("--host", default=os.environ.get(
        "MMLSPARK_TPU_SERVING_HOST", "0.0.0.0"))
    p.add_argument("--port", type=int, default=int(os.environ.get(
        "MMLSPARK_TPU_SERVING_PORT", "8898")))
    p.add_argument("--driver-url", default=os.environ.get(
        "MMLSPARK_TPU_DRIVER_URL", ""))
    p.add_argument("--advertise-host", default=os.environ.get(
        "MMLSPARK_TPU_ADVERTISE_HOST", ""),
        help="peer-routable host registered with the driver (e.g. pod IP); "
             "required whenever binding 0.0.0.0 behind NAT")
    p.add_argument("--worker-id", default=os.environ.get(
        "MMLSPARK_TPU_WORKER_ID", "") or socket.gethostname())
    p.add_argument("--liveness-timeout", type=float, default=30.0)
    args = p.parse_args(argv)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, lambda *_: stop.set())
        except ValueError:
            pass  # non-main thread (tests)

    if args.driver:
        from .distributed import DriverRegistry
        reg = DriverRegistry(host=args.host, port=args.port,
                             liveness_timeout=args.liveness_timeout)
        print(f"driver registry on {reg.url}", flush=True)
        stop.wait()
        reg.close()
        return 0

    if args.driver_url:
        from .distributed import DistributedWorker
        worker = DistributedWorker(args.driver_url, args.worker_id,
                                   host=args.host, port=args.port,
                                   advertise_host=args.advertise_host)
        print(f"worker {args.worker_id} on {worker.advertised_address} "
              f"(driver {args.driver_url})", flush=True)
        stop.wait()
        worker.close()
    else:
        from .server import WorkerServer
        server = WorkerServer(host=args.host, port=args.port)
        print(f"standalone worker on {server.address}", flush=True)
        stop.wait()
        server.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
