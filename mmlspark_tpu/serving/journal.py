"""Durable serving offsets: a write-ahead journal for epochs + requests.

Parity: the reference checkpoints serving progress through Spark's offset
machinery — ``HTTPOffset`` partition→epoch maps and the history queues that
outlive an engine restart (``org/apache/spark/sql/execution/streaming/
continuous/HTTPSourceV2.scala:96-113,225-258,489-506``). There the driver's
checkpoint directory makes epochs durable; here an append-only JSONL journal
per worker plays that role, so a worker **process** restart (not just an
engine restart) rehydrates every routed-but-unanswered request.

Records (one JSON object per line):
    {"t": "req",   "id": ..., "epoch": N, "request": {HTTPRequestData},
     "trace": "32-hex trace id"}          # optional — joins journal lines
                                          # against /debug/traces span trees
    {"t": "rep",   "id": ...}
    {"t": "epoch", "n": N}
    {"t": "sess",     "id": ..., "prompt": [ids], "params": {...},
     "phash": "40-hex prefix hash"}       # a live decode session, written
                                          # at insert (before any compute)
    {"t": "tail",     "id": ..., "toks": [ids]}   # emitted-token tail,
                                          # appended per drain tick
    {"t": "sess_end", "id": ...}          # session completed or retired

Session records make an in-flight *generation* reconstructible from the
journal alone (prompt + sampling params + every emitted token), which is
what driver-orchestrated failover replays through ``/_adopt``: the cold
path re-prefills prompt+tail on a surviving worker (deterministic for
greedy), the warm path ships the KV pages and only needs the tail to know
where decoding resumes.

The write protocol is write-ahead (a request is journaled before it is
visible to the engine), replies are journaled after routing succeeds, and
replay tolerates a truncated final line (the SIGKILL-mid-write case).
Fully-answered epochs are dropped at commit time by compaction.

Delivery semantics: replies are **at-most-once**. The reply record is
appended after the routing-table entry is claimed but before the bytes
reach the client socket, so a crash inside that window marks the request
answered without the client having seen the response; replay will not
rehydrate it. Journaling after the socket write instead would flip this to
at-least-once (duplicate replay of already-delivered replies on restart) —
for an HTTP server, whose client retries on a dropped connection anyway,
at-most-once is the right edge of that trade.
"""

from __future__ import annotations

import json
import os
import threading
import weakref

from ..reliability.lock_sanitizer import new_lock
from typing import Dict, List, Optional, Sequence, Tuple

from ..io.http.schema import HTTPRequestData
from ..observability import counter as _metric_counter
from ..observability import gauge as _metric_gauge
from ..observability import log_event

__all__ = ["ServingJournal"]

M_JOURNAL_BYTES = _metric_gauge(
    "mmlspark_journal_bytes",
    "Bytes on disk across this process's live serving journals (per-journal "
    "values are in ServingJournal.digest() and the watchdog stall bundle)")
M_JOURNAL_RECORDS = _metric_counter(
    "mmlspark_journal_records_total",
    "Journal records appended, by record type", ("type",))
M_JOURNAL_COMPACTIONS = _metric_counter(
    "mmlspark_journal_compactions_total",
    "Journal compactions (atomic rewrite down to the live set)")
M_JOURNAL_REPLAYED_SESS = _metric_counter(
    "mmlspark_journal_replayed_sessions_total",
    "Live decode sessions rehydrated from a journal (restart or /_adopt)")

#: live journals in this process — feeds the bytes gauge and the watchdog
#: stall bundle's ``journal`` block without keeping closed journals alive
_LIVE: "weakref.WeakSet[ServingJournal]" = weakref.WeakSet()


def _refresh_bytes_gauge() -> None:
    M_JOURNAL_BYTES.set(float(sum(j._bytes for j in list(_LIVE))))


def _journal_bundle_block() -> List[dict]:
    return [j.digest() for j in list(_LIVE)]


try:
    from ..observability.watchdog import register_bundle_provider
    register_bundle_provider("journal", _journal_bundle_block)
except Exception as _exc:  # pragma: no cover - watchdog optional at import
    log_event("journal_bundle_provider_unavailable", error=repr(_exc))


class ServingJournal:
    """Append-only JSONL journal with atomic-rename compaction."""

    def __init__(self, path: str, fsync: bool = True):
        self.path = path
        self.fsync = fsync
        self._lock = new_lock("serving.journal.ServingJournal._lock")
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._repair_torn_tail(path)
        self._fh = open(path, "a", encoding="utf-8")
        self._lines_since_compact = 0
        try:
            self._bytes = os.path.getsize(path)
        except OSError:
            self._bytes = 0
        #: per-type append counts since open (digest() + stall bundle)
        self._record_counts: Dict[str, int] = {}
        #: session ids with a "sess" record and no "sess_end" yet
        self._live_sessions: set = set()
        _LIVE.add(self)
        _refresh_bytes_gauge()

    @staticmethod
    def _repair_torn_tail(path: str) -> None:
        """Terminate a non-newline-ended file before appending: without
        this, the first post-restart append would glue onto the torn
        record, corrupting an otherwise-valid line mid-file."""
        try:
            with open(path, "rb") as fh:
                fh.seek(0, os.SEEK_END)
                if fh.tell() == 0:
                    return
                fh.seek(-1, os.SEEK_END)
                last = fh.read(1)
            if last != b"\n":
                with open(path, "ab") as fh:
                    fh.write(b"\n")
        except FileNotFoundError:
            pass

    # -- write side ---------------------------------------------------------
    def _append(self, rec: dict, drop_if_closed: bool = False) -> None:
        with self._lock:
            if self._fh.closed and drop_if_closed:
                # a dispatcher can outlive engine.stop() (join timeout) and
                # reply after close(); losing the reply line only widens the
                # documented at-most-once window, whereas the ValueError
                # would kill the dispatcher thread mid-respond
                import warnings
                warnings.warn("ServingJournal closed; dropping record "
                              f"t={rec.get('t')!r}", RuntimeWarning)
                return
            # note: a closed handle WITHOUT drop_if_closed raises — the
            # write-ahead invariant (server._enqueue) depends on a failed
            # request append erroring the request out before it is queued
            line = json.dumps(rec, separators=(",", ":")) + "\n"
            self._fh.write(line)
            self._fh.flush()
            if self.fsync:
                os.fsync(self._fh.fileno())
            self._lines_since_compact += 1
            self._bytes += len(line.encode("utf-8"))
            t = str(rec.get("t"))
            self._record_counts[t] = self._record_counts.get(t, 0) + 1
            M_JOURNAL_RECORDS.inc(type=t)
            _refresh_bytes_gauge()

    def record_request(self, request_id: str, epoch: int,
                       request: HTTPRequestData,
                       trace_id: Optional[str] = None) -> None:
        rec = {"t": "req", "id": request_id, "epoch": epoch,
               "request": request.to_dict()}
        if trace_id is not None:
            rec["trace"] = trace_id
        self._append(rec)

    def record_reply(self, request_id: str) -> None:
        self._append({"t": "rep", "id": request_id}, drop_if_closed=True)

    def record_epoch(self, epoch: int) -> None:
        self._append({"t": "epoch", "n": epoch}, drop_if_closed=True)

    # -- decode sessions ----------------------------------------------------
    def record_session(self, session_id: str, prompt: Sequence[int],
                       params: dict,
                       phash: Optional[str] = None) -> None:
        """Journal a live decode session at insert time. Write-ahead like
        ``record_request``: a closed journal raises, erroring the submit
        out before any compute is spent on an unrecoverable session."""
        rec = {"t": "sess", "id": session_id,
               "prompt": [int(t) for t in prompt], "params": dict(params)}
        if phash is not None:
            rec["phash"] = phash
        self._append(rec)
        with self._lock:
            self._live_sessions.add(session_id)

    def record_session_tokens(self, session_id: str,
                              tokens: Sequence[int]) -> None:
        """Append one emitted-token tail record (batched per drain tick).
        Dropped when closed: losing a tail only widens the cold-replay
        re-decode window, never corrupts the session."""
        if not tokens:
            return
        self._append({"t": "tail", "id": session_id,
                      "toks": [int(t) for t in tokens]}, drop_if_closed=True)

    def record_session_end(self, session_id: str) -> None:
        self._append({"t": "sess_end", "id": session_id},
                     drop_if_closed=True)
        with self._lock:
            self._live_sessions.discard(session_id)

    # -- recovery side ------------------------------------------------------
    @staticmethod
    def _scan(path: str):
        """Yield records, skipping corrupt lines. A SIGKILL mid-append
        leaves at most one torn record (newline-terminated at next open by
        ``_repair_torn_tail``); skipping — rather than stopping at — a bad
        line preserves everything journaled after an earlier crash."""
        try:
            with open(path, "r", encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        yield json.loads(line)
                    except json.JSONDecodeError:
                        continue
        except FileNotFoundError:
            return

    def replay(self) -> Tuple[int, Dict[str, Tuple[int, HTTPRequestData]]]:
        """Current epoch + unanswered requests ({id: (epoch, request)})."""
        epoch = 0
        pending: Dict[str, Tuple[int, HTTPRequestData]] = {}
        for rec in self._scan(self.path):
            t = rec.get("t")
            if t == "req":
                pending[rec["id"]] = (
                    rec["epoch"], HTTPRequestData.from_dict(rec["request"]))
            elif t == "rep":
                pending.pop(rec["id"], None)
            elif t == "epoch":
                epoch = max(epoch, int(rec["n"]))
        return epoch, pending

    @staticmethod
    def scan_sessions(path: str) -> Dict[str, dict]:
        """Live decode sessions in the journal at ``path``:
        ``{id: {"prompt", "params", "phash", "emitted"}}``. A staticmethod
        on purpose — the driver reads a *dead* worker's journal for cold
        failover without opening the file for append (which would repair
        the tail and race a worker that is merely slow, not dead)."""
        sessions: Dict[str, dict] = {}
        for rec in ServingJournal._scan(path):
            t = rec.get("t")
            if t == "sess":
                sessions[rec["id"]] = {
                    "prompt": list(rec.get("prompt", ())),
                    "params": dict(rec.get("params", {})),
                    "phash": rec.get("phash"),
                    "emitted": [],
                }
            elif t == "tail":
                sess = sessions.get(rec["id"])
                if sess is not None:
                    sess["emitted"].extend(rec.get("toks", ()))
            elif t == "sess_end":
                sessions.pop(rec["id"], None)
        return sessions

    def replay_sessions(self) -> Dict[str, dict]:
        """Rehydrate this journal's live sessions (restart path). Counted
        into ``mmlspark_journal_replayed_sessions_total``."""
        sessions = self.scan_sessions(self.path)
        with self._lock:
            self._live_sessions.update(sessions)
        if sessions:
            M_JOURNAL_REPLAYED_SESS.inc(len(sessions))
        return sessions

    # -- compaction ---------------------------------------------------------
    def maybe_compact(self, epoch: int, min_lines: int = 256) -> bool:
        """Rewrite the journal down to the live set once enough dead lines
        accumulate. Atomic: write a sibling file, fsync, rename over."""
        with self._lock:
            if self._lines_since_compact < min_lines or self._fh.closed:
                return False
            self._fh.flush()
            # one lock span start-to-finish: an append racing between the
            # pending snapshot and the rename would be silently dropped
            # keep the RAW record dicts (not re-parsed request objects) so
            # optional fields ("trace", anything added later) survive the
            # rewrite byte-for-byte
            pending = {}
            sess: Dict[str, dict] = {}
            tails: Dict[str, List[int]] = {}
            for rec in self._scan(self.path):
                t = rec.get("t")
                if t == "req":
                    pending[rec["id"]] = rec
                elif t == "rep":
                    pending.pop(rec["id"], None)
                elif t == "sess":
                    sess[rec["id"]] = rec
                    tails[rec["id"]] = []
                elif t == "tail":
                    if rec["id"] in tails:
                        tails[rec["id"]].extend(rec.get("toks", ()))
                elif t == "sess_end":
                    # an ended session is dead weight: drop its sess record
                    # and every tail line with it
                    sess.pop(rec["id"], None)
                    tails.pop(rec["id"], None)
            tmp = self.path + ".compact"
            with open(tmp, "w", encoding="utf-8") as out:
                out.write(json.dumps({"t": "epoch", "n": epoch},
                                     separators=(",", ":")) + "\n")
                for rec in pending.values():
                    out.write(json.dumps(rec, separators=(",", ":")) + "\n")
                for sid, rec in sess.items():
                    # live sessions survive as sess + ONE merged tail, so
                    # a long decode compacts to two lines, not N drain
                    # ticks' worth
                    out.write(json.dumps(rec, separators=(",", ":")) + "\n")
                    if tails.get(sid):
                        out.write(json.dumps(
                            {"t": "tail", "id": sid, "toks": tails[sid]},
                            separators=(",", ":")) + "\n")
                out.flush()
                os.fsync(out.fileno())
            self._fh.close()
            os.replace(tmp, self.path)
            self._fh = open(self.path, "a", encoding="utf-8")
            self._lines_since_compact = 0
            self._live_sessions = set(sess)
            try:
                self._bytes = os.path.getsize(self.path)
            except OSError:
                pass
            M_JOURNAL_COMPACTIONS.inc()
            _refresh_bytes_gauge()
        return True

    # -- introspection ------------------------------------------------------
    @property
    def closed(self) -> bool:
        return self._fh.closed

    def digest(self) -> dict:
        """Small JSON-able summary for ``/healthz`` digests and the
        watchdog stall bundle's ``journal`` block."""
        with self._lock:
            return {
                "path": self.path,
                "bytes": self._bytes,
                "closed": self._fh.closed,
                "lines_since_compact": self._lines_since_compact,
                "live_sessions": len(self._live_sessions),
                "records": dict(self._record_counts),
            }

    def close(self) -> None:
        with self._lock:
            _LIVE.discard(self)
            _refresh_bytes_gauge()
            try:
                self._fh.close()
            except Exception as exc:
                # a failed close can mean lost journal tail (buffered
                # writes) — worth a trace when chasing replay gaps
                log_event("journal_close_failed", path=self.path,
                          error=repr(exc))
