"""Durable serving offsets: a write-ahead journal for epochs + requests.

Parity: the reference checkpoints serving progress through Spark's offset
machinery — ``HTTPOffset`` partition→epoch maps and the history queues that
outlive an engine restart (``org/apache/spark/sql/execution/streaming/
continuous/HTTPSourceV2.scala:96-113,225-258,489-506``). There the driver's
checkpoint directory makes epochs durable; here an append-only JSONL journal
per worker plays that role, so a worker **process** restart (not just an
engine restart) rehydrates every routed-but-unanswered request.

Records (one JSON object per line):
    {"t": "req",   "id": ..., "epoch": N, "request": {HTTPRequestData},
     "trace": "32-hex trace id"}          # optional — joins journal lines
                                          # against /debug/traces span trees
    {"t": "rep",   "id": ...}
    {"t": "epoch", "n": N}

The write protocol is write-ahead (a request is journaled before it is
visible to the engine), replies are journaled after routing succeeds, and
replay tolerates a truncated final line (the SIGKILL-mid-write case).
Fully-answered epochs are dropped at commit time by compaction.

Delivery semantics: replies are **at-most-once**. The reply record is
appended after the routing-table entry is claimed but before the bytes
reach the client socket, so a crash inside that window marks the request
answered without the client having seen the response; replay will not
rehydrate it. Journaling after the socket write instead would flip this to
at-least-once (duplicate replay of already-delivered replies on restart) —
for an HTTP server, whose client retries on a dropped connection anyway,
at-most-once is the right edge of that trade.
"""

from __future__ import annotations

import json
import os
import threading

from ..reliability.lock_sanitizer import new_lock
from typing import Dict, Optional, Tuple

from ..io.http.schema import HTTPRequestData
from ..observability import log_event

__all__ = ["ServingJournal"]


class ServingJournal:
    """Append-only JSONL journal with atomic-rename compaction."""

    def __init__(self, path: str, fsync: bool = True):
        self.path = path
        self.fsync = fsync
        self._lock = new_lock("serving.journal.ServingJournal._lock")
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._repair_torn_tail(path)
        self._fh = open(path, "a", encoding="utf-8")
        self._lines_since_compact = 0

    @staticmethod
    def _repair_torn_tail(path: str) -> None:
        """Terminate a non-newline-ended file before appending: without
        this, the first post-restart append would glue onto the torn
        record, corrupting an otherwise-valid line mid-file."""
        try:
            with open(path, "rb") as fh:
                fh.seek(0, os.SEEK_END)
                if fh.tell() == 0:
                    return
                fh.seek(-1, os.SEEK_END)
                last = fh.read(1)
            if last != b"\n":
                with open(path, "ab") as fh:
                    fh.write(b"\n")
        except FileNotFoundError:
            pass

    # -- write side ---------------------------------------------------------
    def _append(self, rec: dict, drop_if_closed: bool = False) -> None:
        with self._lock:
            if self._fh.closed and drop_if_closed:
                # a dispatcher can outlive engine.stop() (join timeout) and
                # reply after close(); losing the reply line only widens the
                # documented at-most-once window, whereas the ValueError
                # would kill the dispatcher thread mid-respond
                import warnings
                warnings.warn("ServingJournal closed; dropping record "
                              f"t={rec.get('t')!r}", RuntimeWarning)
                return
            # note: a closed handle WITHOUT drop_if_closed raises — the
            # write-ahead invariant (server._enqueue) depends on a failed
            # request append erroring the request out before it is queued
            self._fh.write(json.dumps(rec, separators=(",", ":")) + "\n")
            self._fh.flush()
            if self.fsync:
                os.fsync(self._fh.fileno())
            self._lines_since_compact += 1

    def record_request(self, request_id: str, epoch: int,
                       request: HTTPRequestData,
                       trace_id: Optional[str] = None) -> None:
        rec = {"t": "req", "id": request_id, "epoch": epoch,
               "request": request.to_dict()}
        if trace_id is not None:
            rec["trace"] = trace_id
        self._append(rec)

    def record_reply(self, request_id: str) -> None:
        self._append({"t": "rep", "id": request_id}, drop_if_closed=True)

    def record_epoch(self, epoch: int) -> None:
        self._append({"t": "epoch", "n": epoch}, drop_if_closed=True)

    # -- recovery side ------------------------------------------------------
    @staticmethod
    def _scan(path: str):
        """Yield records, skipping corrupt lines. A SIGKILL mid-append
        leaves at most one torn record (newline-terminated at next open by
        ``_repair_torn_tail``); skipping — rather than stopping at — a bad
        line preserves everything journaled after an earlier crash."""
        try:
            with open(path, "r", encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        yield json.loads(line)
                    except json.JSONDecodeError:
                        continue
        except FileNotFoundError:
            return

    def replay(self) -> Tuple[int, Dict[str, Tuple[int, HTTPRequestData]]]:
        """Current epoch + unanswered requests ({id: (epoch, request)})."""
        epoch = 0
        pending: Dict[str, Tuple[int, HTTPRequestData]] = {}
        for rec in self._scan(self.path):
            t = rec.get("t")
            if t == "req":
                pending[rec["id"]] = (
                    rec["epoch"], HTTPRequestData.from_dict(rec["request"]))
            elif t == "rep":
                pending.pop(rec["id"], None)
            elif t == "epoch":
                epoch = max(epoch, int(rec["n"]))
        return epoch, pending

    # -- compaction ---------------------------------------------------------
    def maybe_compact(self, epoch: int, min_lines: int = 256) -> bool:
        """Rewrite the journal down to the live set once enough dead lines
        accumulate. Atomic: write a sibling file, fsync, rename over."""
        with self._lock:
            if self._lines_since_compact < min_lines or self._fh.closed:
                return False
            self._fh.flush()
            # one lock span start-to-finish: an append racing between the
            # pending snapshot and the rename would be silently dropped
            # keep the RAW record dicts (not re-parsed request objects) so
            # optional fields ("trace", anything added later) survive the
            # rewrite byte-for-byte
            pending = {}
            for rec in self._scan(self.path):
                if rec.get("t") == "req":
                    pending[rec["id"]] = rec
                elif rec.get("t") == "rep":
                    pending.pop(rec["id"], None)
            tmp = self.path + ".compact"
            with open(tmp, "w", encoding="utf-8") as out:
                out.write(json.dumps({"t": "epoch", "n": epoch},
                                     separators=(",", ":")) + "\n")
                for rec in pending.values():
                    out.write(json.dumps(rec, separators=(",", ":")) + "\n")
                out.flush()
                os.fsync(out.fileno())
            self._fh.close()
            os.replace(tmp, self.path)
            self._fh = open(self.path, "a", encoding="utf-8")
            self._lines_since_compact = 0
        return True

    def close(self) -> None:
        with self._lock:
            try:
                self._fh.close()
            except Exception as exc:
                # a failed close can mean lost journal tail (buffered
                # writes) — worth a trace when chasing replay gaps
                log_event("journal_close_failed", path=self.path,
                          error=repr(exc))
