"""Tenant-fair admission and prefix-affine placement primitives.

Two pieces of the multi-tenant traffic plane live here, deliberately
transport-free so they unit-test without sockets:

- :class:`AdmissionQueue` — a drop-in for the ``queue.Queue`` surface
  :class:`~mmlspark_tpu.serving.server.WorkerServer` uses (``full`` /
  ``put_nowait`` / ``put`` / ``get`` / ``get_nowait`` / ``qsize`` /
  ``maxsize``), but internally deficit-round-robin over per-tenant FIFOs:
  each tenant's share of dequeues tracks its configured weight, a burst
  from one tenant cannot starve the rest, and admission sheds the
  over-budget tenant FIRST (``TenantOverBudget``, a ``queue.Full``
  subclass so existing shed paths keep working). The queue also measures
  its own drain rate (EWMA of dequeue intervals) so 429 ``Retry-After``
  hints can reflect the live backlog instead of a static knob.

- :class:`ConsistentHashRing` — virtual-node consistent hashing with
  bounded-load fallback, the placement structure behind prefix-affine
  request routing in ``serving/distributed.py``: keys (KV-prefix hashes
  from ``PagedKVPool.prefix_hash``) map to the same worker across
  membership changes except for the 1/n of keyspace a joined/left node
  actually owns — unlike ``hash(key) % len(peers)`` (tpulint TPU016),
  which reshuffles every key on any membership change.

Within a single tenant FIFO order is preserved, so the epoch/replay
semantics of the worker server are unchanged; with one active tenant the
whole structure degenerates to the old single FIFO.
"""

from __future__ import annotations

import bisect
import hashlib
import queue
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Mapping, Optional

from ..observability import counter as _metric_counter
from ..observability import gauge as _metric_gauge

__all__ = ["AdmissionQueue", "TenantOverBudget", "ConsistentHashRing"]

_M_WFQ_ENQ = _metric_counter(
    "mmlspark_wfq_enqueued_total",
    "Requests admitted into the weighted-fair admission queue",
    ("tenant",))
_M_WFQ_DEQ = _metric_counter(
    "mmlspark_wfq_dequeued_total",
    "Requests dequeued from the weighted-fair admission queue (DRR order)",
    ("tenant",))
_M_WFQ_SHED = _metric_counter(
    "mmlspark_wfq_shed_total",
    "Requests refused by tenant-aware admission control",
    ("tenant", "reason"))
_M_RING_REBUILDS = _metric_counter(
    "mmlspark_ring_rebuilds_total",
    "Consistent-hash ring rebuilds (worker join/leave/restart)")
_M_RING_ROUTES = _metric_counter(
    "mmlspark_ring_routes_total",
    "Keyed routing decisions by outcome: affine (first ring choice), "
    "fallback (bounded-load walked past an overloaded owner)",
    ("outcome",))
_M_RING_WORKERS = _metric_gauge(
    "mmlspark_ring_workers",
    "Live workers currently on the consistent-hash ring")

#: tenants beyond this many distinct metric labels collapse to "other" —
#: same cardinality discipline as the SLO tracker's MAX_CLASSES
MAX_TENANT_LABELS = 32

_tenant_labels: set = set()
_tenant_labels_lock = threading.Lock()


def _tenant_label(tenant: str) -> str:
    """Cardinality-bounded metric label for a free-form tenant string."""
    t = str(tenant)
    with _tenant_labels_lock:
        if t in _tenant_labels:
            return t
        if len(_tenant_labels) < MAX_TENANT_LABELS:
            _tenant_labels.add(t)
            return t
    return "other"


class TenantOverBudget(queue.Full):
    """One tenant exceeded its weighted share of the queue while capacity
    remains for others — subclasses ``queue.Full`` so every existing
    full-queue handling path (shed, enqueue race-undo) treats it as a
    shed, while carrying enough context to scale ``Retry-After`` to the
    offender's deficit."""

    def __init__(self, tenant: str, depth: int, budget: int):
        super().__init__()
        self.tenant = tenant
        self.depth = depth
        self.budget = budget
        self.reason = "tenant_budget"


class AdmissionQueue:
    """Deficit-round-robin weighted-fair queue over per-tenant FIFOs.

    ``weight_fn(tenant) -> float`` supplies tenant weights (typically
    ``ModelRegistry.tenant_weight``); unknown tenants weigh 1. Dequeue
    order gives each *backlogged* tenant a per-round quantum proportional
    to its weight, so under contention goodput shares track weights.

    ``maxsize`` bounds total depth exactly like ``queue.Queue``. On top
    of that, each tenant's standing backlog is budgeted at its weighted
    share of ``maxsize`` times ``burst`` (headroom so a lone tenant can
    still use the whole queue): :meth:`check_admit` / :meth:`put_nowait`
    raise :class:`TenantOverBudget` for the over-budget tenant before
    the global ``queue.Full``. :meth:`put` bypasses budgets — it is the
    replay path, and already-admitted requests must never be dropped.
    """

    #: floor for configured weights, so a zero/negative weight cannot
    #: stall the DRR scan or zero a tenant's budget entirely
    MIN_WEIGHT = 1e-3
    #: EWMA smoothing for the dequeue-interval estimate
    DRAIN_ALPHA = 0.2
    #: ceiling for suggested Retry-After hints (seconds)
    MAX_RETRY_AFTER = 30.0
    #: a dequeue gap beyond this is an idle period, not a drain interval:
    #: it neither feeds the EWMA nor lets a stale estimate answer
    #: suggest_retry_after (the time-series history answers instead)
    IDLE_GAP_SECONDS = 5.0

    def __init__(self, maxsize: int = 0,
                 weight_fn: Optional[Callable[[str], float]] = None,
                 burst: float = 2.0):
        self.maxsize = int(maxsize)
        self.burst = float(burst)
        self._weight_fn = weight_fn
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        #: tenant → FIFO of parked items (only backlogged tenants present)
        self._queues: Dict[str, deque] = {}
        #: active-tenant round order + DRR scan position
        self._order: List[str] = []
        self._cursor = 0
        self._deficits: Dict[str, float] = {}
        self._size = 0
        # drain-rate EWMA state (seconds between dequeues)
        self._last_dequeue: Optional[float] = None
        self._ewma_interval: Optional[float] = None
        #: label value tying this queue to its ``mmlspark_queue_drain_rate``
        #: series in the time-series store (WorkerServer sets the port);
        #: None means no history — suggest_retry_after falls back to the
        #: live EWMA alone
        self.history_key: Optional[str] = None

    # -- weights / budgets --------------------------------------------------
    def _weight(self, tenant: str) -> float:
        if self._weight_fn is None:
            return 1.0
        try:
            w = float(self._weight_fn(tenant))
        except Exception:
            w = 1.0
        return max(w, self.MIN_WEIGHT)

    def _budget_locked(self, tenant: str) -> int:
        """Tenant backlog budget: weighted share of maxsize with ``burst``
        headroom, computed over the tenants currently backlogged plus the
        arriving one. A lone tenant's budget is >= maxsize (the global
        bound is the only limit — old FIFO behavior)."""
        if self.maxsize <= 0:
            return 1 << 30
        active = set(self._order)
        active.add(tenant)
        total_w = sum(self._weight(t) for t in active)
        share = self._weight(tenant) / total_w if total_w > 0 else 1.0
        return max(1, int(self.maxsize * share * self.burst))

    # -- queue.Queue surface ------------------------------------------------
    def qsize(self) -> int:
        return self._size

    def empty(self) -> bool:
        return self._size == 0

    def full(self) -> bool:
        return 0 < self.maxsize <= self._size

    def depth(self, tenant: str) -> int:
        with self._lock:
            q = self._queues.get(str(tenant))
            return len(q) if q is not None else 0

    def depths(self) -> Dict[str, int]:
        with self._lock:
            return {t: len(q) for t, q in self._queues.items()}

    def check_admit(self, tenant: str) -> None:
        """Raise ``queue.Full`` (global) or :class:`TenantOverBudget`
        (tenant over its weighted share) if admitting one more request
        for ``tenant`` should shed instead. Advisory — the authoritative
        check re-runs inside :meth:`put_nowait` (admission race)."""
        tenant = str(tenant)
        with self._lock:
            self._check_admit_locked(tenant)

    def _check_admit_locked(self, tenant: str) -> None:
        if 0 < self.maxsize <= self._size:
            _M_WFQ_SHED.inc(tenant=_tenant_label(tenant),
                            reason="queue_full")
            raise queue.Full
        q = self._queues.get(tenant)
        depth = len(q) if q is not None else 0
        budget = self._budget_locked(tenant)
        if depth >= budget:
            _M_WFQ_SHED.inc(tenant=_tenant_label(tenant),
                            reason="tenant_budget")
            raise TenantOverBudget(tenant, depth, budget)

    def put_nowait(self, item) -> None:
        tenant = str(getattr(item, "tenant", "default"))
        with self._lock:
            self._check_admit_locked(tenant)
            self._append_locked(item, tenant)
        _M_WFQ_ENQ.inc(tenant=_tenant_label(tenant))

    def put(self, item) -> None:
        """Unconditional append — the replay/rehydration path. Requests
        that were already admitted once must survive an engine restart
        even when budgets have tightened in between."""
        tenant = str(getattr(item, "tenant", "default"))
        with self._lock:
            self._append_locked(item, tenant)
        _M_WFQ_ENQ.inc(tenant=_tenant_label(tenant))

    def _append_locked(self, item, tenant: str) -> None:
        q = self._queues.get(tenant)
        if q is None:
            q = self._queues[tenant] = deque()
            self._order.append(tenant)
            # a newly-backlogged tenant starts its round with zero banked
            # deficit — idle time earns no credit
            self._deficits[tenant] = 0.0
        q.append(item)
        self._size += 1
        self._not_empty.notify()

    def get(self, block: bool = True, timeout: Optional[float] = None):
        with self._not_empty:
            if not block:
                if self._size == 0:
                    raise queue.Empty
                return self._pop_locked()
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            while self._size == 0:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise queue.Empty
                self._not_empty.wait(remaining)
            return self._pop_locked()

    def get_nowait(self):
        return self.get(block=False)

    # -- DRR core -----------------------------------------------------------
    def _retire_locked(self, tenant: str) -> None:
        idx = self._order.index(tenant)
        self._order.pop(idx)
        if idx < self._cursor:
            self._cursor -= 1
        self._deficits.pop(tenant, None)
        self._queues.pop(tenant, None)

    def _pop_locked(self):
        """One DRR dequeue. Each visit tops a tenant's deficit up by its
        weight once per round; the tenant then serves consecutive items
        while deficit >= 1, so per-round quanta (and hence drain shares)
        are proportional to weights. Guaranteed to terminate: size > 0
        means some FIFO is non-empty and every full scan cycle adds at
        least MIN_WEIGHT to its deficit."""
        while True:
            if self._cursor >= len(self._order):
                self._cursor = 0
            tenant = self._order[self._cursor]
            q = self._queues.get(tenant)
            if not q:
                self._retire_locked(tenant)
                continue
            if self._deficits[tenant] < 1.0:
                self._deficits[tenant] += self._weight(tenant)
            if self._deficits[tenant] >= 1.0:
                self._deficits[tenant] -= 1.0
                item = q.popleft()
                self._size -= 1
                if not q:
                    self._retire_locked(tenant)
                elif self._deficits[tenant] < 1.0:
                    self._cursor += 1   # quantum spent — next tenant
                self._note_dequeue_locked()
                _M_WFQ_DEQ.inc(tenant=_tenant_label(tenant))
                return item
            self._cursor += 1

    # -- drain rate / Retry-After -------------------------------------------
    def _note_dequeue_locked(self) -> None:
        now = time.monotonic()
        if self._last_dequeue is not None:
            dt = max(now - self._last_dequeue, 1e-6)
            # an idle gap is not a drain interval: folding it in used to
            # wreck the estimate for many EWMA steps after a lull (the
            # first post-idle 429 then suggested a near-ceiling
            # Retry-After). Re-anchor and keep the pre-idle estimate.
            if dt <= self.IDLE_GAP_SECONDS:
                if self._ewma_interval is None:
                    self._ewma_interval = dt
                else:
                    self._ewma_interval = (self.DRAIN_ALPHA * dt
                                           + (1 - self.DRAIN_ALPHA)
                                           * self._ewma_interval)
        self._last_dequeue = now

    def drain_rate(self) -> float:
        """Estimated dequeues/second (EWMA over recent intervals); 0.0
        until two dequeues have been observed."""
        with self._lock:
            iv = self._ewma_interval
        if iv is None or iv <= 0:
            return 0.0
        return 1.0 / iv

    def suggest_retry_after(self, floor: float = 1.0,
                            tenant: Optional[str] = None) -> float:
        """Load-aware 429 ``Retry-After``: current backlog over the
        measured drain rate, clamped to ``[floor, MAX_RETRY_AFTER]``.
        For a tenant shed over budget, scaled up by how far over budget
        that tenant is (its deficit), so the worst offender backs off
        hardest. ``floor`` keeps the configured static knob as a lower
        bound.

        After an idle gap (or before two dequeues have ever happened)
        the live EWMA knows nothing — the estimate is seeded from the
        time-series store's measured ``mmlspark_queue_drain_rate``
        history for this queue's ``history_key``, so the first 429 after
        a lull carries a realistic hint instead of the floor. Falls back
        to the live EWMA when the store is cold."""
        rate = self.drain_rate()
        with self._lock:
            last = self._last_dequeue
        stale = (last is None
                 or time.monotonic() - last > self.IDLE_GAP_SECONDS)
        if rate <= 0 or stale:
            seeded = self._history_drain_rate()
            if seeded is not None:
                rate = seeded
                # adopt the seed so drain_rate()/snapshot() agree with
                # the hint until live dequeues take over again
                with self._lock:
                    self._ewma_interval = 1.0 / seeded
        hint = (self._size / rate) if rate > 0 else floor
        if tenant is not None:
            with self._lock:
                q = self._queues.get(str(tenant))
                depth = len(q) if q is not None else 0
                budget = self._budget_locked(str(tenant))
            if budget > 0 and depth > budget:
                hint *= depth / budget
        return round(min(max(hint, floor), self.MAX_RETRY_AFTER), 3)

    def _history_drain_rate(self) -> Optional[float]:
        """Recent measured drain rate from the time-series store (the
        sampler records ``mmlspark_queue_drain_rate{port}`` every tick),
        or None when unkeyed / cold / unavailable. Queried outside the
        queue lock — the store takes its own."""
        key = self.history_key
        if key is None:
            return None
        try:
            # lazy: observability.timeseries must stay importable without
            # the serving plane and vice versa
            from ..observability.timeseries import get_store
            rate = get_store().ewma("mmlspark_queue_drain_rate",
                                    seconds=120.0, labels={"port": key})
        except Exception:
            return None
        if rate is None or rate <= 0:
            return None
        return float(rate)

    def snapshot(self) -> Dict[str, object]:
        """JSON-safe admission state for debug routes and heartbeats."""
        with self._lock:
            depths = {t: len(q) for t, q in self._queues.items()}
            deficits = {t: round(d, 4) for t, d in self._deficits.items()}
        return {"size": self._size, "maxsize": self.maxsize,
                "tenants": depths, "deficits": deficits,
                "drain_rate": round(self.drain_rate(), 4)}


def _ring_hash(data: str) -> int:
    """Stable 64-bit ring position (sha1 — same family as
    ``PagedKVPool.prefix_hash``, and NOT Python's salted ``hash()``)."""
    return int.from_bytes(
        hashlib.sha1(data.encode("utf-8")).digest()[:8], "big")


class ConsistentHashRing:
    """Consistent hashing with virtual nodes and bounded-load fallback.

    ``rebuild(nodes)`` replaces the membership (idempotent — same set is
    a no-op); ``route(key, load)`` returns the owning node for a key,
    walking to the next distinct ring position when the owner's current
    ``load`` exceeds ``load_factor`` times the mean (the bounded-load
    variant of consistent hashing), so a hot prefix cannot pin-down an
    overloaded worker. With ``replicas`` virtual nodes per member, a
    membership change moves only ~1/n of the keyspace.
    """

    def __init__(self, replicas: int = 64, load_factor: float = 1.25):
        self.replicas = max(1, int(replicas))
        self.load_factor = float(load_factor)
        self._lock = threading.Lock()
        self._nodes: tuple = ()
        self._hashes: List[int] = []
        self._owners: List[str] = []

    def rebuild(self, nodes: Iterable[str]) -> bool:
        """Set ring membership; True when the membership actually changed
        (counted in ``mmlspark_ring_rebuilds_total``)."""
        members = tuple(sorted({str(n) for n in nodes}))
        with self._lock:
            if members == self._nodes:
                return False
            points = []
            for node in members:
                for i in range(self.replicas):
                    points.append((_ring_hash(f"{node}#{i}"), node))
            points.sort()
            self._nodes = members
            self._hashes = [h for h, _ in points]
            self._owners = [n for _, n in points]
        _M_RING_REBUILDS.inc()
        _M_RING_WORKERS.set(len(members))
        return True

    def nodes(self) -> tuple:
        with self._lock:
            return self._nodes

    def __len__(self) -> int:
        return len(self.nodes())

    def preferred(self, key: str, n: Optional[int] = None) -> List[str]:
        """Distinct nodes in ring order starting at ``key``'s position —
        the affinity owner first, then each bounded-load fallback."""
        with self._lock:
            if not self._nodes:
                return []
            want = len(self._nodes) if n is None else min(n, len(self._nodes))
            start = bisect.bisect_left(self._hashes, _ring_hash(str(key)))
            out: List[str] = []
            for i in range(len(self._owners)):
                node = self._owners[(start + i) % len(self._owners)]
                if node not in out:
                    out.append(node)
                    if len(out) >= want:
                        break
            return out

    def route(self, key: str,
              load: Optional[Mapping[str, float]] = None) -> Optional[str]:
        """Owning node for ``key``; with a ``load`` map (node → in-flight
        count), falls back along the ring past nodes above
        ``load_factor`` x mean load. None on an empty ring."""
        order = self.preferred(key)
        if not order:
            return None
        if not load:
            _M_RING_ROUTES.inc(outcome="affine")
            return order[0]
        total = sum(float(load.get(n, 0)) for n in order)
        cap = self.load_factor * (total + 1) / len(order)
        for i, node in enumerate(order):
            if float(load.get(node, 0)) < cap:
                _M_RING_ROUTES.inc(outcome="affine" if i == 0
                                   else "fallback")
                return node
        # every node above cap (uniformly overloaded): the affinity owner
        # is still the best choice — its pool holds the prefix pages
        _M_RING_ROUTES.inc(outcome="affine")
        return order[0]
