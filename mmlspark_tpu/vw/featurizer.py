"""VowpalWabbitFeaturizer / VowpalWabbitInteractions equivalents.

Parity surface: ``vw/.../VowpalWabbitFeaturizer.scala`` (+ the 11 typed
featurizers under ``vw/.../featurizer/*.scala``) and
``VowpalWabbitInteractions.scala``. Each input column is a *namespace*; its
values are hashed into a shared 2^num_bits index space:

* numeric scalar  → one feature: ``h(column)``, value = x (zeros skipped,
  as ``featurizer/NumericFeaturizer.scala`` does)
* bool            → feature ``h(column)`` with value 1.0 when true
* str             → feature ``h(column ␟ value)`` with value 1.0
  (``featurizer/StringFeaturizer.scala``)
* list/array of str → one feature per element
  (``featurizer/StringArrayFeaturizer.scala``)
* numeric ndarray → position-indexed features ``(ns_seed + i) & mask``
  (``featurizer/VectorFeaturizer.scala`` uses in-namespace indices)
* dict            → ``h(column ␟ key)`` → float(value)
  (``featurizer/MapFeaturizer.scala``)

The output column holds ``(indices uint32[nnz], values float32[nnz])`` per
row — the framework's sparse-vector convention for the VW learners, which
pad these to static ``[batch, max_nnz]`` device arrays.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import HasInputCols, HasOutputCol, Param
from ..core.pipeline import Transformer
from .murmur import combine_hashes, namespace_seed

__all__ = ["VowpalWabbitFeaturizer", "VowpalWabbitInteractions",
           "NUM_BITS_KEY", "sparse_column", "max_nnz"]

#: column-metadata key carrying the hash-space size
NUM_BITS_KEY = "vw_num_bits"

_SEP = "\x1f"  # namespace/value separator fed to the hash


def sparse_column(rows: List) -> np.ndarray:
    out = np.empty(len(rows), dtype=object)
    for i, r in enumerate(rows):
        out[i] = r
    return out


def max_nnz(col: np.ndarray) -> int:
    return max((len(r[0]) for r in col), default=0)


def _is_string_col(col: np.ndarray) -> bool:
    if col.dtype.kind == "U":
        return True
    if col.dtype == object:
        return all(v is None or isinstance(v, str) for v in col)
    return False


def _dedupe_sum(idx: np.ndarray, val: np.ndarray):
    """Sum values of colliding indices (``sumCollisions`` in the reference)."""
    if len(idx) < 2:
        return idx, val
    uniq, inv = np.unique(idx, return_inverse=True)
    if len(uniq) == len(idx):
        return idx, val
    summed = np.zeros(len(uniq), dtype=np.float32)
    np.add.at(summed, inv, val)
    return uniq.astype(np.uint32), summed


class VowpalWabbitFeaturizer(Transformer, HasInputCols, HasOutputCol):
    """Hash arbitrary columns into one sparse feature namespace column."""

    num_bits = Param(int, default=18, doc="log2 size of the hashed feature space")
    sum_collisions = Param(bool, default=True,
                           doc="sum values of colliding feature indices "
                               "(vs keep duplicates)")
    string_split_cols = Param((list, str), default=[],
                              doc="string columns to whitespace-split into "
                                  "multiple token features")
    seed = Param(int, default=0, doc="base murmur seed")

    def __init__(self, **kw):
        super().__init__(**kw)
        self._set_default(output_col="features")

    def _featurize_value(self, v, col: str, ns_seed: int, mask: int,
                         split: bool, idx_out: list, val_out: list):
        from .murmur import murmur3_32
        if v is None:
            return
        if isinstance(v, (bool, np.bool_)):
            if v:
                idx_out.append(ns_seed & mask)
                val_out.append(1.0)
        elif isinstance(v, (int, float, np.integer, np.floating)):
            if v != 0:
                idx_out.append(ns_seed & mask)
                val_out.append(float(v))
        elif isinstance(v, str):
            tokens = v.split() if split else [v]
            for t in tokens:
                idx_out.append(
                    murmur3_32((col + _SEP + t).encode("utf-8"), ns_seed) & mask)
                val_out.append(1.0)
        elif isinstance(v, dict):
            for k, x in v.items():
                fx = float(x)
                if fx != 0:
                    idx_out.append(
                        murmur3_32((col + _SEP + str(k)).encode("utf-8"),
                                   ns_seed) & mask)
                    val_out.append(fx)
        elif isinstance(v, (list, tuple, np.ndarray)):
            arr = np.asarray(v)
            if arr.dtype.kind in "iuf":
                nz = np.nonzero(arr.ravel())[0]
                for i in nz:
                    idx_out.append((ns_seed + int(i)) & mask)
                    val_out.append(float(arr.ravel()[i]))
            else:
                for t in arr.ravel():
                    self._featurize_value(t, col, ns_seed, mask, split,
                                          idx_out, val_out)
        else:
            raise TypeError(f"cannot featurize {type(v).__name__} in column {col!r}")

    def _transform(self, df: DataFrame) -> DataFrame:
        cols = self.get("input_cols")
        if not cols:
            raise ValueError("input_cols must be set")
        bits = self.get("num_bits")
        mask = (1 << bits) - 1
        split_cols = set(self.get("string_split_cols"))
        seeds = {c: namespace_seed(c, self.get("seed")) for c in cols}
        n = len(df)
        idx_rows: list = [[] for _ in range(n)]
        val_rows: list = [[] for _ in range(n)]
        for c in cols:
            col = df[c]
            split = c in split_cols
            if _is_string_col(col):
                # column-major batch hash through the native fast path —
                # the host-side equivalent of VW's C++ example parser
                from ..native import murmur3_batch
                toks_per_row = [[] if v is None else
                                (v.split() if split else [v]) for v in col]
                flat = [(c + _SEP + t).encode("utf-8")
                        for toks in toks_per_row for t in toks]
                hashed = murmur3_batch(flat, seeds[c], mask)
                off = 0
                for r, toks in enumerate(toks_per_row):
                    k = len(toks)
                    if k:
                        idx_rows[r].append(hashed[off:off + k])
                        val_rows[r].append(np.ones(k, np.float32))
                    off += k
            else:
                for r in range(n):
                    io: list = []
                    vo: list = []
                    self._featurize_value(col[r], c, seeds[c], mask, split,
                                          io, vo)
                    if io:
                        idx_rows[r].append(np.asarray(io, dtype=np.uint32))
                        val_rows[r].append(np.asarray(vo, dtype=np.float32))
        rows = []
        for r in range(n):
            idx = (np.concatenate(idx_rows[r]).astype(np.uint32)
                   if idx_rows[r] else np.array([], dtype=np.uint32))
            val = (np.concatenate(val_rows[r])
                   if val_rows[r] else np.array([], dtype=np.float32))
            if self.get("sum_collisions"):
                idx, val = _dedupe_sum(idx, val)
            rows.append((idx, val))
        out = df.with_column(self.get("output_col"), sparse_column(rows))
        return out.with_column_metadata(self.get("output_col"),
                                        {NUM_BITS_KEY: bits})


class VowpalWabbitInteractions(Transformer, HasInputCols, HasOutputCol):
    """Cross N sparse namespaces into interaction features.

    Parity: ``VowpalWabbitInteractions.scala`` — the cartesian product of the
    listed namespaces, combined with VW's FNV multiply-xor hash, value =
    product of the crossed feature values.
    """

    num_bits = Param(int, default=18, doc="log2 size of the hashed feature space")
    sum_collisions = Param(bool, default=True,
                           doc="sum values of colliding interaction indices")

    def __init__(self, **kw):
        super().__init__(**kw)
        self._set_default(output_col="interactions")

    def _transform(self, df: DataFrame) -> DataFrame:
        cols = self.get("input_cols")
        if len(cols) < 2:
            raise ValueError("interactions need >= 2 input namespaces")
        mask = (1 << self.get("num_bits")) - 1
        n = len(df)
        rows = []
        for r in range(n):
            idx, val = df[cols[0]][r]
            idx = np.asarray(idx, dtype=np.uint32)
            val = np.asarray(val, dtype=np.float32)
            for c in cols[1:]:
                i2, v2 = df[c][r]
                i2 = np.asarray(i2, dtype=np.uint32)
                v2 = np.asarray(v2, dtype=np.float32)
                # cartesian cross of the accumulated namespace with the next
                ia = np.repeat(idx, len(i2))
                ib = np.tile(i2, len(idx))
                idx = combine_hashes(ia, ib, mask)
                val = np.repeat(val, len(v2)) * np.tile(v2, len(val))
            if self.get("sum_collisions"):
                idx, val = _dedupe_sum(idx, val)
            rows.append((idx, val))
        out = df.with_column(self.get("output_col"), sparse_column(rows))
        return out.with_column_metadata(self.get("output_col"),
                                        {NUM_BITS_KEY: self.get("num_bits")})
