"""Contextual bandit learner on hashed features.

Parity surface: ``VowpalWabbitContextualBandit``
(``vw/.../VowpalWabbitContextualBandit.scala``, 376 LoC): per-example action
sets with shared features, a chosen action (1-based), its observed cost and
logging probability; cost-sensitive learning with IPS or importance-weighted
regression ("mtr"-style) estimators; parallel ``fitMultiple`` for param
sweeps.

Design: each (shared, action) pair is crossed with the FNV interaction hash —
the same namespace-crossing VW performs for ``--cb_explore_adf`` — and a cost
regressor is trained on the chosen action's crossed features with importance
weight 1/p (clipped). Prediction scores every action and returns the
argmin-cost action plus an epsilon-greedy pmf.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import List, Sequence

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, HasLabelCol, Param
from ..core.pipeline import Estimator, Model
from .featurizer import NUM_BITS_KEY, sparse_column
from .learners import VowpalWabbitRegressor, pad_sparse
from .murmur import combine_hashes

__all__ = ["VowpalWabbitContextualBandit", "VowpalWabbitContextualBanditModel"]


def _cross(shared, action, mask: int):
    """Cross shared-namespace features with one action's features."""
    si, sv = np.asarray(shared[0], np.uint32), np.asarray(shared[1], np.float32)
    ai, av = np.asarray(action[0], np.uint32), np.asarray(action[1], np.float32)
    if len(si) == 0:
        return ai & np.uint32(mask), av
    if len(ai) == 0:
        return si & np.uint32(mask), sv
    ia = np.repeat(si, len(ai))
    ib = np.tile(ai, len(si))
    idx = combine_hashes(ia, ib, mask)
    val = np.repeat(sv, len(av)) * np.tile(av, len(sv))
    # keep the raw action features too, as VW's ADF examples carry both the
    # action namespace and its interaction with the shared namespace
    return (np.concatenate([ai & np.uint32(mask), idx]),
            np.concatenate([av, val]))


class VowpalWabbitContextualBandit(Estimator, HasLabelCol):
    """Learn action costs from logged bandit feedback."""

    shared_col = Param(str, default="shared", doc="shared-features column "
                                                  "((indices, values) rows)")
    features_col = Param(str, default="features",
                         doc="per-action features column: each row is a list "
                             "of (indices, values), one per action")
    chosen_action_col = Param(str, default="chosenAction",
                              doc="1-based index of the logged action")
    probability_col = Param(str, default="probability",
                            doc="logging probability of the chosen action")
    cb_type = Param(str, default="ips", choices=["ips", "mtr"],
                    doc="cost estimator: inverse-propensity-scaled regression "
                        "(ips) or plain importance-weighted regression (mtr)")
    epsilon = Param(float, default=0.05, doc="exploration for the output pmf")
    prob_clip = Param(float, default=0.05,
                      doc="lower clip on logging probabilities (caps IPS "
                          "importance weights)")
    num_bits = Param(int, default=18, doc="log2 weight-space size")
    num_passes = Param(int, default=1, doc="passes over the data")
    learning_rate = Param(float, default=0.5, doc="base learning rate")
    l1 = Param(float, default=0.0, doc="L1 regularization")
    l2 = Param(float, default=0.0, doc="L2 regularization")
    mini_batch = Param(int, default=64, doc="rows per device update step")

    def _num_bits(self, df: DataFrame) -> int:
        meta = df.column_metadata(self.get("features_col"))
        return int(meta.get(NUM_BITS_KEY, self.get("num_bits")))

    def _fit(self, df: DataFrame) -> "VowpalWabbitContextualBanditModel":
        bits = self._num_bits(df)
        mask = (1 << bits) - 1
        shared = df[self.get("shared_col")]
        actions = df[self.get("features_col")]
        chosen = np.asarray(df[self.get("chosen_action_col")], dtype=np.int64)
        cost = np.asarray(df[self.get("label_col")], dtype=np.float32)
        prob = np.asarray(df[self.get("probability_col")], dtype=np.float32)

        rows = []
        weights = []
        clip = self.get("prob_clip")
        for r in range(len(df)):
            acts = actions[r]
            if len(acts) == 0:
                raise ValueError(f"row {r}: empty action list")
            c = int(chosen[r])
            if not 1 <= c <= len(acts):
                raise ValueError(
                    f"row {r}: chosen_action {c} out of range 1..{len(acts)} "
                    "(VW actions are 1-based)")
            a = acts[c - 1]                          # 1-based (VW convention)
            rows.append(_cross(shared[r], a, mask))
            if self.get("cb_type") == "ips":
                weights.append(1.0 / max(float(prob[r]), clip))
            else:                                     # mtr: plain IW regression
                weights.append(1.0)

        train_df = DataFrame({
            "features": sparse_column(rows),
            "cost": cost,
            "iw": np.asarray(weights, dtype=np.float32),
        }).with_column_metadata("features", {NUM_BITS_KEY: bits})

        reg = VowpalWabbitRegressor(
            features_col="features", label_col="cost", weight_col="iw",
            num_passes=self.get("num_passes"),
            learning_rate=self.get("learning_rate"),
            l1=self.get("l1"), l2=self.get("l2"),
            mini_batch=self.get("mini_batch"), num_bits=bits)
        inner = reg.fit(train_df)

        m = VowpalWabbitContextualBanditModel()
        m.set(shared_col=self.get("shared_col"),
              features_col=self.get("features_col"),
              epsilon=self.get("epsilon"),
              weights=np.asarray(inner.get("weights")), num_bits=bits)
        m.performance_statistics = inner.performance_statistics
        return m

    def fit_multiple(self, df: DataFrame, param_maps: Sequence[dict]) -> List[Model]:
        """Parallel multi-model fit (parity:
        ``VowpalWabbitContextualBandit.fitMultiple``)."""
        with ThreadPoolExecutor(max_workers=min(4, max(1, len(param_maps)))) as ex:
            return list(ex.map(lambda m: self.fit(df, dict(m)), param_maps))


class VowpalWabbitContextualBanditModel(Model):
    shared_col = Param(str, default="shared", doc="shared-features column")
    features_col = Param(str, default="features", doc="per-action features column")
    prediction_col = Param(str, default="prediction",
                           doc="output: argmin-cost action (1-based)")
    scores_col = Param(str, default="scores", doc="output: per-action costs")
    pmf_col = Param(str, default="pmf", doc="output: epsilon-greedy action pmf")
    epsilon = Param(float, default=0.05, doc="exploration mass")
    weights = ComplexParam(default=None, doc="hashed weight vector")
    num_bits = Param(int, default=18, doc="log2 weight-space size")

    def _transform(self, df: DataFrame) -> DataFrame:
        mask = (1 << self.get("num_bits")) - 1
        w = np.asarray(self.get("weights"))
        shared = df[self.get("shared_col")]
        actions = df[self.get("features_col")]
        eps = self.get("epsilon")
        pred = np.zeros(len(df), dtype=np.int64)
        scores_col = np.empty(len(df), dtype=object)
        pmf_col = np.empty(len(df), dtype=object)
        for r in range(len(df)):
            if len(actions[r]) == 0:
                raise ValueError(f"row {r}: empty action list")
            crossed = [_cross(shared[r], a, mask) for a in actions[r]]
            idx, val = pad_sparse(sparse_column(crossed))
            scores = (w[idx] * val).sum(axis=1)
            k = len(scores)
            best = int(scores.argmin())
            pmf = np.full(k, eps / k)
            pmf[best] += 1.0 - eps
            pred[r] = best + 1
            scores_col[r] = scores.astype(np.float32)
            pmf_col[r] = pmf.astype(np.float32)
        return (df.with_column(self.get("prediction_col"), pred)
                  .with_column(self.get("scores_col"), scores_col)
                  .with_column(self.get("pmf_col"), pmf_col))
