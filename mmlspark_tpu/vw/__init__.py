"""VW-equivalent module: hashed-feature online linear learning.

Parity surface: the reference's ``vw`` module (SURVEY.md §2.4) —
``VowpalWabbitFeaturizer`` (columns → murmur-hashed namespaces),
``VowpalWabbitInteractions`` (namespace crossing),
``VowpalWabbitClassifier``/``VowpalWabbitRegressor`` (online SGD with
per-pass AllReduce, ``vw/.../VowpalWabbitBase.scala:432-460``), and
``VowpalWabbitContextualBandit``.

TPU-native redesign: no C++ VW core and no spanning-tree daemon. Hashing is
host-side (murmur3, same family as ``VowpalWabbitMurmurWithPrefix.scala``);
the learner is a single jitted ``lax.scan`` over minibatches doing
adagrad-scaled sparse updates (gather + scatter-add, which XLA lowers to
efficient TPU scatters), and distributed data parallelism is per-pass weight
averaging with ``jax.lax.pmean`` over a device mesh — the XLA-collective
equivalent of VW's ``--span_server`` AllReduce.
"""

from .featurizer import VowpalWabbitFeaturizer, VowpalWabbitInteractions
from .learners import (VowpalWabbitClassifier, VowpalWabbitClassifierModel,
                       VowpalWabbitRegressor, VowpalWabbitRegressorModel)
from .bandit import (VowpalWabbitContextualBandit,
                     VowpalWabbitContextualBanditModel)

__all__ = [
    "VowpalWabbitFeaturizer", "VowpalWabbitInteractions",
    "VowpalWabbitClassifier", "VowpalWabbitClassifierModel",
    "VowpalWabbitRegressor", "VowpalWabbitRegressorModel",
    "VowpalWabbitContextualBandit", "VowpalWabbitContextualBanditModel",
]
