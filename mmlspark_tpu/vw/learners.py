"""VW-style online linear learners on hashed sparse features.

Parity surface: ``VowpalWabbitClassifier`` / ``VowpalWabbitRegressor`` and the
training orchestration of ``VowpalWabbitBase`` (``vw/.../VowpalWabbitBase.scala``):
multiple passes over the data, adaptive (adagrad) importance-weighted updates,
squared / logistic / hinge / quantile losses, per-pass distributed weight
AllReduce (``--span_server``, ``VowpalWabbitBase.scala:432-460``), and a
per-fit performance-statistics table (``TrainingStats``,
``VowpalWabbitBase.scala:25-47,473-487``).

TPU-native redesign (not a port): VW's per-example C++ loop becomes one jitted
``lax.scan`` over fixed-size minibatches. Each step gathers the touched
weights (``w[idx]``), computes the loss gradient, and scatter-adds adagrad
statistics and updates — XLA lowers gather/scatter to native TPU ops, and the
whole multi-pass optimization is a single compiled program. Data parallelism
shards rows over a mesh axis and averages weights with ``lax.pmean`` after
every pass, exactly the synchronization VW's spanning-tree AllReduce performs.
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import (ComplexParam, HasFeaturesCol, HasLabelCol,
                           HasPredictionCol, HasProbabilityCol, HasWeightCol,
                           Param)
from ..core.pipeline import Estimator, Model
from .featurizer import NUM_BITS_KEY

__all__ = ["VowpalWabbitClassifier", "VowpalWabbitClassifierModel",
           "VowpalWabbitRegressor", "VowpalWabbitRegressorModel"]


# ---------------------------------------------------------------------------
# Sparse batch marshalling: object rows → padded static-shape device arrays
# ---------------------------------------------------------------------------

def pad_sparse(col, max_nnz: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
    """(indices, values) object rows → (idx [n, K] int32, val [n, K] f32).

    Padding slots get index 0 with value 0 — a zero-value feature is a no-op
    for both prediction (contributes 0) and the gradient (scales by value).
    """
    if max_nnz is None:
        max_nnz = max((len(r[0]) for r in col), default=0)
    K = max(1, max_nnz)
    from ..native import pad_sparse as native_pad
    return native_pad(list(col), K)


def _make_pass_fn(loss: str, quantile_tau: float, n_passes: int,
                  batch: int, axis: Optional[str]):
    """Build the jitted multi-pass trainer. ``axis`` names the mesh axis to
    pmean weights over after each pass (None = single device)."""
    import jax
    import jax.numpy as jnp

    def dloss(pred, y, sw):
        if loss == "squared":
            return (pred - y) * sw
        if loss == "logistic":        # y in {-1, +1}
            return -y * jax.nn.sigmoid(-y * pred) * sw
        if loss == "hinge":           # y in {-1, +1}
            return jnp.where(y * pred < 1.0, -y, 0.0) * sw
        if loss == "quantile":
            return jnp.where(pred > y, 1.0 - quantile_tau, -quantile_tau) * sw
        raise ValueError(f"unknown loss {loss!r}")

    def run(w, G, idx, val, y, sw, lr, l1, l2, power_t):
        """idx/val: [n_batches, B, K]; y/sw: [n_batches, B]."""
        if axis is not None:
            # entering shard_map replicated; updates indexed by sharded rows
            # make the carry device-varying, so mark it varying up front
            pvary = getattr(jax.lax, "pvary", None)
            if pvary is not None:
                w = pvary(w, (axis,))
                G = pvary(G, (axis,))
            else:
                w = jax.lax.pcast(w, (axis,), to="varying")
                G = jax.lax.pcast(G, (axis,), to="varying")

        def minibatch_step(carry, xs):
            w, G, t = carry
            bidx, bval, by, bsw = xs
            pred = jnp.sum(w[bidx] * bval, axis=-1)          # [B] gather+dot
            d = dloss(pred, by, bsw)                          # [B]
            g = d[:, None] * bval                             # [B, K] per-feature grad
            # adagrad accumulate, then scale: scatter-adds coalesce duplicate
            # indices inside the batch, which is the correct sum-of-squares /
            # summed-gradient semantics for minibatch adagrad
            G = G.at[bidx].add(g * g)
            denom = jnp.sqrt(G[bidx]) + 1e-6
            # decayed base rate: lr * (t+1)^-power_t, VW's power_t schedule
            step = lr * (t + 1.0) ** (-power_t)
            upd = step * g / denom
            w = w.at[bidx].add(-upd)
            # proximal-ish shrinkage on touched coords only (sparse l1/l2);
            # padding slots (index 0, value 0) must not count as touched or
            # bucket 0 gets over-regularized every step
            if True:
                wt = w[bidx]
                shrunk = jnp.sign(wt) * jnp.maximum(jnp.abs(wt) - step * l1, 0.0)
                shrunk = shrunk * (1.0 - step * l2)
                w = w.at[bidx].set(jnp.where(bval != 0.0, shrunk, wt))
            return (w, G, t + 1.0), None

        def one_pass(carry, _):
            w, G, t = carry
            (w, G, t), _ = jax.lax.scan(minibatch_step, (w, G, t),
                                        (idx, val, y, sw))
            if axis is not None:
                w = jax.lax.pmean(w, axis)   # per-pass AllReduce (VW parity)
                pvary = getattr(jax.lax, "pvary", None)
                w = (pvary(w, (axis,)) if pvary is not None
                     else jax.lax.pcast(w, (axis,), to="varying"))
            return (w, G, t), None

        (w, G, _), _ = jax.lax.scan(one_pass, (w, G, 0.0), None,
                                    length=n_passes)
        if axis is not None:
            # replicate the outputs: w is already synced (identity pmean);
            # G merges into an averaged accumulator for warm starts
            w = jax.lax.pmean(w, axis)
            G = jax.lax.pmean(G, axis)
        return w, G

    return run


_PASS_CACHE: dict = {}


def _pass_fn(loss, tau, n_passes, batch, axis):
    import jax
    key = (loss, float(tau), int(n_passes), int(batch), axis)
    if key not in _PASS_CACHE:
        _PASS_CACHE[key] = jax.jit(_make_pass_fn(loss, tau, n_passes, batch, axis))
    return _PASS_CACHE[key]


# ---------------------------------------------------------------------------
# Base estimator
# ---------------------------------------------------------------------------

class _VWParams(HasFeaturesCol, HasLabelCol, HasWeightCol):
    num_passes = Param(int, default=1, doc="passes over the data")
    learning_rate = Param(float, default=0.5, doc="base learning rate (VW default 0.5)")
    power_t = Param(float, default=0.5, doc="learning-rate decay exponent")
    l1 = Param(float, default=0.0, doc="L1 regularization (per-update shrink)")
    l2 = Param(float, default=0.0, doc="L2 regularization (per-update decay)")
    num_bits = Param(int, default=18, doc="log2 weight-space size; overridden "
                                          "by featurizer column metadata")
    mini_batch = Param(int, default=64, doc="rows per device update step "
                                            "(TPU-first stand-in for VW's "
                                            "per-example loop)")
    use_all_reduce = Param(bool, default=True,
                           doc="shard rows over the default mesh and pmean "
                               "weights each pass (VW --span_server parity)")
    initial_model = ComplexParam(default=None, doc="warm-start weight vector")
    initial_adaptive_state = ComplexParam(
        default=None, doc="warm-start adagrad accumulator (VW --save_resume "
                          "parity; take it from a fitted model's "
                          "adaptive_state param)")
    seed = Param(int, default=0, doc="unused (training is deterministic); "
                                     "kept for API parity")


class _VWBase(Estimator, _VWParams):
    _loss: str = "squared"
    quantile_tau = Param(float, default=0.5, doc="tau for quantile loss")

    def _labels(self, df: DataFrame) -> np.ndarray:
        raise NotImplementedError

    def _num_bits(self, df: DataFrame) -> int:
        meta = df.column_metadata(self.get("features_col"))
        return int(meta.get(NUM_BITS_KEY, self.get("num_bits")))

    def _fit(self, df: DataFrame) -> "Model":
        t0 = time.perf_counter()
        import jax
        import jax.numpy as jnp

        fcol = df[self.get("features_col")]
        bits = self._num_bits(df)
        dim = 1 << bits
        idx, val = pad_sparse(fcol)
        n, K = idx.shape
        y = self._labels(df).astype(np.float32)
        wcol = self.get_or_none("weight_col")
        sw = (df[wcol].astype(np.float32) if wcol
              else np.ones(n, dtype=np.float32))

        B = min(self.get("mini_batch"), max(1, n))
        # shard rows across the default mesh when requested & available
        from ..parallel.mesh import get_default_mesh
        mesh = get_default_mesh() if self.get("use_all_reduce") else None
        n_shards = int(np.prod(list(mesh.shape.values()))) if mesh is not None else 1

        # pad row count to n_shards * B multiple with zero-weight rows
        per = -(-n // (n_shards * B)) * B            # rows per shard, multiple of B
        total = per * n_shards
        pad = total - n
        if pad:
            idx = np.vstack([idx, np.zeros((pad, K), np.int32)])
            val = np.vstack([val, np.zeros((pad, K), np.float32)])
            y = np.concatenate([y, np.zeros(pad, np.float32)])
            sw = np.concatenate([sw, np.zeros(pad, np.float32)])

        w0 = self.get_or_none("initial_model")
        w0 = (np.zeros(dim, np.float32) if w0 is None
              else np.asarray(w0, np.float32).copy())
        if len(w0) != dim:
            raise ValueError(f"initial_model has {len(w0)} weights, expected {dim}")
        G0 = self.get_or_none("initial_adaptive_state")
        G0 = (np.full(dim, 1e-12, np.float32) if G0 is None
              else np.asarray(G0, np.float32).copy())

        n_batches = per // B
        tau = self.get("quantile_tau")
        passes = self.get("num_passes")
        lr = jnp.float32(self.get("learning_rate"))
        l1 = jnp.float32(self.get("l1"))
        l2 = jnp.float32(self.get("l2"))
        pt = jnp.float32(self.get("power_t"))

        if mesh is not None and n_shards > 1:
            from jax.sharding import PartitionSpec as P

            from ..parallel.mesh import get_shard_map
            shard_map, _ = get_shard_map()
            axis = mesh.axis_names[0]
            run = _make_pass_fn(self._loss, tau, passes, B, axis)

            def sharded(w, G, idx, val, y, sw):
                w, G = run(w.reshape(-1), G.reshape(-1),
                           idx.reshape(n_batches, B, K),
                           val.reshape(n_batches, B, K),
                           y.reshape(n_batches, B), sw.reshape(n_batches, B),
                           lr, l1, l2, pt)
                return w, G

            spec_rows = P(axis)
            fn = jax.jit(shard_map(
                sharded, mesh=mesh,
                in_specs=(P(), P(), spec_rows, spec_rows, spec_rows, spec_rows),
                out_specs=(P(), P())))
            w, G = fn(jnp.asarray(w0), jnp.asarray(G0), jnp.asarray(idx),
                      jnp.asarray(val), jnp.asarray(y), jnp.asarray(sw))
        else:
            run = _pass_fn(self._loss, tau, passes, B, None)
            w, G = run(jnp.asarray(w0), jnp.asarray(G0),
                       jnp.asarray(idx.reshape(n_batches, B, K)),
                       jnp.asarray(val.reshape(n_batches, B, K)),
                       jnp.asarray(y.reshape(n_batches, B)),
                       jnp.asarray(sw.reshape(n_batches, B)),
                       lr, l1, l2, pt)
        w = np.asarray(jax.block_until_ready(w))

        model = self._make_model()
        model.set(features_col=self.get("features_col"),
                  weights=w, num_bits=bits,
                  adaptive_state=np.asarray(G))
        elapsed = time.perf_counter() - t0
        # TrainingStats parity (VowpalWabbitBase.scala:25-47): one row per
        # data shard with timing/size diagnostics
        model.performance_statistics = DataFrame({
            "partitionId": np.arange(n_shards),
            "rows": np.full(n_shards, n // max(n_shards, 1)),
            "passes": np.full(n_shards, passes),
            "totalSeconds": np.full(n_shards, round(elapsed, 4)),
            "weightsNonZero": np.full(n_shards, int((w != 0).sum())),
        })
        return model

    def _make_model(self) -> "Model":
        raise NotImplementedError


class _VWModelBase(Model, HasFeaturesCol, HasPredictionCol):
    weights = ComplexParam(default=None, doc="hashed weight vector (2^num_bits)")
    adaptive_state = ComplexParam(default=None,
                                  doc="adagrad accumulator for warm starts")
    num_bits = Param(int, default=18, doc="log2 weight-space size")

    def _raw_scores(self, df: DataFrame) -> np.ndarray:
        idx, val = pad_sparse(df[self.get("features_col")])
        w = np.asarray(self.get("weights"))
        return (w[idx] * val).sum(axis=1)


class VowpalWabbitRegressor(_VWBase, HasPredictionCol):
    """Online linear regression (squared or quantile loss)."""

    loss_function = Param(str, default="squared",
                          choices=["squared", "quantile"],
                          doc="training loss")

    @property
    def _loss(self):
        return self.get("loss_function")

    def _labels(self, df: DataFrame) -> np.ndarray:
        return np.asarray(df[self.get("label_col")], dtype=np.float32)

    def _make_model(self):
        m = VowpalWabbitRegressorModel()
        m.set(prediction_col=self.get("prediction_col"))
        return m


class VowpalWabbitRegressorModel(_VWModelBase):
    def _transform(self, df: DataFrame) -> DataFrame:
        return df.with_column(self.get("prediction_col"), self._raw_scores(df))


class VowpalWabbitClassifier(_VWBase, HasPredictionCol, HasProbabilityCol):
    """Binary classifier (labels {0,1}), logistic or hinge loss."""

    loss_function = Param(str, default="logistic",
                          choices=["logistic", "hinge"],
                          doc="training loss")

    @property
    def _loss(self):
        return self.get("loss_function")

    def _labels(self, df: DataFrame) -> np.ndarray:
        y = np.asarray(df[self.get("label_col")], dtype=np.float32)
        uniq = np.unique(y)
        if not np.all(np.isin(uniq, [0.0, 1.0, -1.0])):
            raise ValueError(f"binary labels must be 0/1 (or ±1), got {uniq}")
        return np.where(y > 0, 1.0, -1.0)   # VW's ±1 convention

    def _make_model(self):
        m = VowpalWabbitClassifierModel()
        m.set(prediction_col=self.get("prediction_col"),
              probability_col=self.get("probability_col"))
        return m


class VowpalWabbitClassifierModel(_VWModelBase, HasProbabilityCol):
    raw_prediction_col = Param(str, default="rawPrediction",
                               doc="column for the raw margin")

    def _transform(self, df: DataFrame) -> DataFrame:
        raw = self._raw_scores(df)
        prob = 1.0 / (1.0 + np.exp(-raw))
        return (df.with_column(self.get("raw_prediction_col"), raw)
                  .with_column(self.get("probability_col"), prob)
                  .with_column(self.get("prediction_col"),
                               (raw > 0).astype(np.float64)))
