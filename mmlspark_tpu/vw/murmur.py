"""MurmurHash3 (x86 32-bit) for VW-style feature hashing.

Role of ``VowpalWabbitMurmurWithPrefix`` in the reference
(``vw/.../VowpalWabbitMurmurWithPrefix.scala``): hash feature names into a
2^num_bits index space, with the column/namespace name folded in as a seed or
prefix so identical feature names in different namespaces don't collide.
"""

from __future__ import annotations

import numpy as np

__all__ = ["murmur3_32", "namespace_seed", "hash_feature", "combine_hashes",
           "FNV_PRIME"]

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_M32 = 0xFFFFFFFF

#: FNV-1a prime, used (as VW does) to combine hashes for feature interactions.
FNV_PRIME = 0x01000193


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _M32


def murmur3_32(data: bytes, seed: int = 0) -> int:
    """MurmurHash3 x86_32 of ``data`` with ``seed``; returns uint32.

    Dispatches to the native extension when built (this pure-Python body is
    the reference implementation and the fallback)."""
    global _native_fn
    if _native_fn is None:
        try:
            from .. import native
            impl = native._load()
            _native_fn = impl.murmur3 if impl else False
        except Exception:
            _native_fn = False
    if _native_fn:
        return _native_fn(data, seed & _M32)
    return _murmur3_32_py(data, seed)


_native_fn = None


def _murmur3_32_py(data: bytes, seed: int = 0) -> int:
    h = seed & _M32
    n = len(data)
    nblocks = n // 4
    for i in range(nblocks):
        k = int.from_bytes(data[4 * i:4 * i + 4], "little")
        k = (k * _C1) & _M32
        k = _rotl(k, 15)
        k = (k * _C2) & _M32
        h ^= k
        h = _rotl(h, 13)
        h = (h * 5 + 0xE6546B64) & _M32
    # tail
    k = 0
    tail = data[nblocks * 4:]
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * _C1) & _M32
        k = _rotl(k, 15)
        k = (k * _C2) & _M32
        h ^= k
    # finalization
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _M32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _M32
    h ^= h >> 16
    return h


def namespace_seed(namespace: str, seed: int = 0) -> int:
    """Hash of the namespace (column) name — the per-namespace seed, as VW
    seeds feature hashes with the namespace hash."""
    return murmur3_32(namespace.encode("utf-8"), seed)


def hash_feature(name: str, ns_seed: int, mask: int) -> int:
    """Hash a feature name inside a namespace into [0, mask]."""
    return murmur3_32(name.encode("utf-8"), ns_seed) & mask


def combine_hashes(h1: np.ndarray, h2: np.ndarray, mask: int) -> np.ndarray:
    """FNV-style interaction combine (VW's quadratic feature hash):
    ``(h1 * FNV_PRIME) XOR h2``, masked into the weight space. Works on
    scalars or numpy arrays."""
    a = (np.asarray(h1, dtype=np.uint64) * np.uint64(FNV_PRIME)) & np.uint64(_M32)
    out = (a ^ np.asarray(h2, dtype=np.uint64)) & np.uint64(mask)
    return out.astype(np.uint32)
