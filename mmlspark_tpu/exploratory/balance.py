"""Data balance analysis (Responsible AI).

Parity surface: ``FeatureBalanceMeasure:38``, ``DistributionBalanceMeasure:38``,
``AggregateBalanceMeasure:30`` (reference ``core/.../exploratory/*.scala``):
fairness/association measures between sensitive-feature values and labels,
per-feature distribution distances vs a uniform reference, and aggregate
inequality indices.
"""

from __future__ import annotations

import itertools
from typing import Dict

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import HasLabelCol, Param
from ..core.pipeline import Transformer

__all__ = ["FeatureBalanceMeasure", "DistributionBalanceMeasure",
           "AggregateBalanceMeasure"]


class FeatureBalanceMeasure(Transformer, HasLabelCol):
    """Pairwise association gaps between values of each sensitive column."""

    sensitive_cols = Param((list, str), default=[], doc="sensitive columns")
    verbose = Param(bool, default=False, doc="parity flag")

    def _transform(self, df: DataFrame) -> DataFrame:
        y = df[self.get("label_col")].astype(np.float64)
        pos = y == 1
        n = len(df)
        p_pos = pos.mean() if n else 0.0
        rows = []
        for col in self.get("sensitive_cols"):
            vals = df[col]
            uniq = sorted({v.item() if isinstance(v, np.generic) else v
                           for v in vals}, key=str)
            stats: Dict = {}
            for v in uniq:
                mask = np.asarray([x == v for x in vals])
                p_a = mask.mean()
                p_pos_a = (mask & pos).mean()
                p_pos_given_a = p_pos_a / p_a if p_a else 0.0
                stats[v] = (p_a, p_pos_a, p_pos_given_a)
            for a, b in itertools.combinations(uniq, 2):
                pa, ppa, ppga = stats[a]
                pb, ppb, ppgb = stats[b]
                def _pmi(pp, p):
                    return np.log(pp / (p * p_pos)) if pp > 0 and p > 0 \
                        and p_pos > 0 else float("-inf")
                rows.append({
                    "FeatureName": col, "ClassA": a, "ClassB": b,
                    "dp": ppga - ppgb,                      # statistical parity
                    "pmi": _pmi(ppa, pa) - _pmi(ppb, pb),   # pointwise MI gap
                    "sdc": ppa / (pa + p_pos) - ppb / (pb + p_pos),
                    "ji": ppa / (pa + p_pos - ppa) - ppb / (pb + p_pos - ppb),
                    "krc": _krc(pa, ppa, p_pos, n) - _krc(pb, ppb, p_pos, n),
                    "llr": (np.log(ppa / p_pos) if ppa > 0 else float("-inf"))
                           - (np.log(ppb / p_pos) if ppb > 0 else float("-inf")),
                })
        return DataFrame.from_rows(rows)


def _krc(p_a, p_pos_a, p_pos, n) -> float:
    """Kendall rank correlation term (reference FeatureBalanceMeasure)."""
    if n == 0 or p_a in (0.0, 1.0):
        return 0.0
    a = p_pos_a
    b = p_a - p_pos_a          # feature, not label
    c = p_pos - p_pos_a        # label, not feature
    d = 1.0 - p_a - c          # neither
    denom = np.sqrt((a + b) * (c + d) * (a + c) * (b + d))
    return float((a * d - b * c) / denom) if denom else 0.0


class DistributionBalanceMeasure(Transformer):
    """Per-column distribution distances vs the uniform reference."""

    sensitive_cols = Param((list, str), default=[], doc="columns to measure")

    def _transform(self, df: DataFrame) -> DataFrame:
        rows = []
        n = len(df)
        for col in self.get("sensitive_cols"):
            vals = df[col]
            uniq, counts = np.unique(vals, return_counts=True)
            p = counts / n
            k = len(uniq)
            ref = np.full(k, 1.0 / k)
            with np.errstate(divide="ignore", invalid="ignore"):
                kl = float(np.sum(p * np.log(p / ref)))
            m = 0.5 * (p + ref)
            js = float(0.5 * np.sum(p * np.log(p / m))
                       + 0.5 * np.sum(ref * np.log(ref / m)))
            chi2 = float(n * np.sum((p - ref) ** 2 / ref))
            rows.append({
                "FeatureName": col,
                "kl_divergence": kl,
                "js_dist": float(np.sqrt(js)),
                "inf_norm_dist": float(np.abs(p - ref).max()),
                "total_variation_dist": float(0.5 * np.abs(p - ref).sum()),
                "wasserstein_dist": float(np.abs(np.cumsum(p) -
                                                 np.cumsum(ref)).mean()),
                "chi_sq_stat": chi2,
            })
        return DataFrame.from_rows(rows)


class AggregateBalanceMeasure(Transformer):
    """Inequality indices over the joint sensitive-feature distribution."""

    sensitive_cols = Param((list, str), default=[], doc="columns to combine")
    epsilon = Param(float, default=1.0, doc="Atkinson inequality aversion")

    def _transform(self, df: DataFrame) -> DataFrame:
        cols = self.get("sensitive_cols")
        combos = list(zip(*(df[c] for c in cols)))
        _, counts = np.unique([str(c) for c in combos], return_counts=True)
        p = counts / counts.sum()
        k = len(p)
        mu = 1.0 / k
        eps = self.get("epsilon")
        if eps == 1.0:
            atkinson = 1.0 - np.power(np.prod(p / mu), 1.0 / k)
        else:
            atkinson = 1.0 - np.power(
                np.mean(np.power(p / mu, 1.0 - eps)), 1.0 / (1.0 - eps))
        theil_t = float(np.sum((p / mu) * np.log(p / mu)) / k)
        theil_l = float(np.sum(np.log(mu / p)) / k)
        return DataFrame.from_rows([{
            "atkinson_index": float(atkinson),
            "theil_t_index": theil_t,
            "theil_l_index": theil_l,
        }])
