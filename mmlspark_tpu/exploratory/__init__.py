from .balance import (AggregateBalanceMeasure, DistributionBalanceMeasure,
                      FeatureBalanceMeasure)

__all__ = ["FeatureBalanceMeasure", "DistributionBalanceMeasure",
           "AggregateBalanceMeasure"]
