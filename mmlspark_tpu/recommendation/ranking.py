"""Ranking evaluation & tuning.

Parity surface: ``RecommendationIndexer:18`` (string ids → dense indices),
``RankingEvaluator:100`` (NDCG@k, MAP@k, precision@k, recall@k),
``RankingAdapter:69`` (learner → per-user top-k lists),
``RankingTrainValidationSplit:25`` (reference
``core/.../recommendation/*.scala``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param
from ..core.pipeline import Estimator, Model, Transformer

__all__ = ["RecommendationIndexer", "RecommendationIndexerModel",
           "RankingEvaluator", "RankingAdapter", "RankingTrainValidationSplit"]


class RecommendationIndexer(Estimator):
    user_input_col = Param(str, default="user", doc="raw user id column")
    user_output_col = Param(str, default="user_idx", doc="indexed user column")
    item_input_col = Param(str, default="item", doc="raw item id column")
    item_output_col = Param(str, default="item_idx", doc="indexed item column")

    def _fit(self, df: DataFrame) -> "RecommendationIndexerModel":
        def levels(col):
            return sorted({v.item() if isinstance(v, np.generic) else v
                           for v in col}, key=str)
        m = RecommendationIndexerModel()
        m.set(user_input_col=self.get("user_input_col"),
              user_output_col=self.get("user_output_col"),
              item_input_col=self.get("item_input_col"),
              item_output_col=self.get("item_output_col"),
              user_levels=levels(df[self.get("user_input_col")]),
              item_levels=levels(df[self.get("item_input_col")]))
        return m


class RecommendationIndexerModel(Model):
    user_input_col = Param(str, default="user", doc="raw user id column")
    user_output_col = Param(str, default="user_idx", doc="indexed user column")
    item_input_col = Param(str, default="item", doc="raw item id column")
    item_output_col = Param(str, default="item_idx", doc="indexed item column")
    user_levels = Param(list, default=[], doc="user values by index")
    item_levels = Param(list, default=[], doc="item values by index")

    def _transform(self, df: DataFrame) -> DataFrame:
        out = df
        for inp, outp, lv in ((self.get("user_input_col"),
                               self.get("user_output_col"),
                               self.get("user_levels")),
                              (self.get("item_input_col"),
                               self.get("item_output_col"),
                               self.get("item_levels"))):
            table = {v: i for i, v in enumerate(lv)}
            idx = np.asarray([table[v.item() if isinstance(v, np.generic)
                                    else v] for v in df[inp]], dtype=np.int64)
            out = out.with_column(outp, idx)
        return out

    def recover_user(self, idx: int):
        return self.get("user_levels")[idx]

    def recover_item(self, idx: int):
        return self.get("item_levels")[idx]


def _ndcg_at_k(pred: Sequence, truth: Sequence, k: int) -> float:
    truth_set = set(truth)
    dcg = sum(1.0 / np.log2(i + 2) for i, p in enumerate(pred[:k])
              if p in truth_set)
    idcg = sum(1.0 / np.log2(i + 2) for i in range(min(k, len(truth_set))))
    return dcg / idcg if idcg else 0.0


def _map_at_k(pred: Sequence, truth: Sequence, k: int) -> float:
    truth_set = set(truth)
    if not truth_set:
        return 0.0
    hits, score = 0, 0.0
    for i, p in enumerate(pred[:k]):
        if p in truth_set:
            hits += 1
            score += hits / (i + 1.0)
    return score / min(len(truth_set), k)


class RankingEvaluator(Transformer):
    """Consumes a frame with per-user prediction lists and truth lists."""

    k = Param(int, default=10, doc="cutoff")
    prediction_col = Param(str, default="recommendations",
                           doc="per-user predicted item list")
    label_col = Param(str, default="labels", doc="per-user relevant item list")
    metric_name = Param(str, default="ndcgAt",
                        choices=["ndcgAt", "map", "precisionAtk", "recallAtK"],
                        doc="headline metric")

    def evaluate(self, df: DataFrame) -> float:
        row = self._transform(df)
        return float(row[self.get("metric_name")][0])

    def _transform(self, df: DataFrame) -> DataFrame:
        k = self.get("k")
        preds = df[self.get("prediction_col")]
        truths = df[self.get("label_col")]
        ndcg, maps, precs, recs = [], [], [], []
        for p, t in zip(preds, truths):
            p, t = list(p), list(t)
            ndcg.append(_ndcg_at_k(p, t, k))
            maps.append(_map_at_k(p, t, k))
            hits = len(set(p[:k]) & set(t))
            precs.append(hits / float(k))
            recs.append(hits / float(len(t)) if t else 0.0)
        return DataFrame.from_rows([{
            "ndcgAt": float(np.mean(ndcg)) if ndcg else 0.0,
            "map": float(np.mean(maps)) if maps else 0.0,
            "precisionAtk": float(np.mean(precs)) if precs else 0.0,
            "recallAtK": float(np.mean(recs)) if recs else 0.0,
        }])


class RankingAdapter(Estimator):
    """Fit a recommender and emit per-user top-k lists next to the ground
    truth, ready for RankingEvaluator (reference ``RankingAdapter.scala:69``)."""

    recommender = ComplexParam(default=None, doc="estimator producing a model "
                               "with recommend_for_all_users")
    k = Param(int, default=10, doc="items per user")
    user_col = Param(str, default="user", doc="user id column")
    item_col = Param(str, default="item", doc="item id column")

    def _fit(self, df: DataFrame) -> "RankingAdapterModel":
        model = self.get("recommender").fit(df)
        m = RankingAdapterModel()
        m.set(recommender_model=model, k=self.get("k"),
              user_col=self.get("user_col"), item_col=self.get("item_col"))
        return m


class RankingAdapterModel(Model):
    recommender_model = ComplexParam(default=None, doc="fitted recommender")
    k = Param(int, default=10, doc="items per user")
    user_col = Param(str, default="user", doc="user id column")
    item_col = Param(str, default="item", doc="item id column")

    def _transform(self, df: DataFrame) -> DataFrame:
        recs = self.get("recommender_model").recommend_for_all_users(
            self.get("k"))
        rec_map = dict(zip(recs[self.get("user_col")],
                           recs["recommendations"]))
        users = df[self.get("user_col")].astype(np.int64)
        items = df[self.get("item_col")]
        truth: Dict[int, List] = {}
        for u, i in zip(users, items):
            truth.setdefault(int(u), []).append(
                i.item() if isinstance(i, np.generic) else i)
        uniq = sorted(truth)
        out_pred = np.empty(len(uniq), dtype=object)
        out_truth = np.empty(len(uniq), dtype=object)
        for j, u in enumerate(uniq):
            out_pred[j] = list(rec_map.get(u, []))
            out_truth[j] = truth[u]
        return DataFrame({self.get("user_col"): np.asarray(uniq),
                          "recommendations": out_pred, "labels": out_truth})


class RankingTrainValidationSplit(Estimator):
    """Per-user train/validation split + evaluation of a recommender
    (reference ``RankingTrainValidationSplit.scala:25``)."""

    recommender = ComplexParam(default=None, doc="estimator to evaluate")
    train_ratio = Param(float, default=0.75, doc="per-user train fraction")
    user_col = Param(str, default="user", doc="user id column")
    item_col = Param(str, default="item", doc="item id column")
    k = Param(int, default=10, doc="evaluation cutoff")
    seed = Param(int, default=0, doc="split seed")

    validation_metrics: Optional[dict] = None

    def _fit(self, df: DataFrame) -> Model:
        rng = np.random.default_rng(self.get("seed"))
        users = df[self.get("user_col")].astype(np.int64)
        train_mask = np.zeros(len(df), dtype=bool)
        for u in np.unique(users):
            idx = np.flatnonzero(users == u)
            n_train = max(1, int(round(self.get("train_ratio") * len(idx))))
            chosen = rng.permutation(idx)[:n_train]
            train_mask[chosen] = True
        train, valid = df.filter(train_mask), df.filter(~train_mask)

        adapter = RankingAdapter(recommender=self.get("recommender"),
                                 k=self.get("k"),
                                 user_col=self.get("user_col"),
                                 item_col=self.get("item_col"))
        adapter_model = adapter.fit(train)
        ranked = adapter_model.transform(valid)
        ev = RankingEvaluator(k=self.get("k"))
        metrics = ev.transform(ranked)
        self.validation_metrics = {c: float(metrics[c][0])
                                   for c in metrics.columns}
        return adapter_model
