from .ranking import (RankingAdapter, RankingAdapterModel, RankingEvaluator,
                      RankingTrainValidationSplit, RecommendationIndexer,
                      RecommendationIndexerModel)
from .sar import SAR, SARModel

__all__ = [
    "SAR", "SARModel",
    "RecommendationIndexer", "RecommendationIndexerModel",
    "RankingEvaluator", "RankingAdapter", "RankingAdapterModel",
    "RankingTrainValidationSplit",
]
