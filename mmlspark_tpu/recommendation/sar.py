"""Smart Adaptive Recommendations (SAR).

Parity surface: ``SAR:36`` / ``SARModel:22`` (reference
``core/.../recommendation/SAR.scala``): item-item similarity from
co-occurrence (jaccard / lift / cooccurrence counts) + per-user affinity with
exponential time decay; recommendation = affinity · similarity.

TPU-first: both the co-occurrence C = Aᵀ·A and the scoring affinity ·
similarity products are single MXU matmuls under ``jit``.
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param
from ..core.pipeline import Estimator, Model

__all__ = ["SAR", "SARModel"]

from ..utils.jit_cache import jitted as _jitted


class SAR(Estimator):
    user_col = Param(str, default="user", doc="user id column (int indices)")
    item_col = Param(str, default="item", doc="item id column (int indices)")
    rating_col = Param(str, default="rating", doc="rating column (optional)")
    time_col = Param(str, default=None, doc="timestamp column for decay")
    similarity_function = Param(str, default="jaccard",
                                choices=["jaccard", "lift", "cooccurrence"],
                                doc="item-item similarity")
    support_threshold = Param(int, default=4,
                              doc="min co-occurrence count to keep")
    time_decay_coeff = Param(int, default=30,
                             doc="half-life in days for affinity decay")

    def _fit(self, df: DataFrame) -> "SARModel":
        import jax.numpy as jnp

        users = df[self.get("user_col")].astype(np.int64)
        items = df[self.get("item_col")].astype(np.int64)
        n_users = int(users.max()) + 1 if len(users) else 0
        n_items = int(items.max()) + 1 if len(items) else 0

        rcol = self.get_or_none("rating_col")
        ratings = (df[rcol].astype(np.float64) if rcol and rcol in df
                   else np.ones(len(df)))

        # affinity with exponential time decay (reference: user affinity
        # a_u,i = sum_k r_k * 2^(-(t0 - t_k)/T))
        tcol = self.get_or_none("time_col")
        if tcol and tcol in df:
            t = df[tcol].astype(np.float64)
            t0 = t.max()
            half_life_s = self.get("time_decay_coeff") * 86400.0
            decay = np.power(2.0, -(t0 - t) / half_life_s)
        else:
            decay = np.ones(len(df))

        A = np.zeros((n_users, n_items), dtype=np.float32)
        np.add.at(A, (users, items), ratings * decay)
        occ = np.zeros((n_users, n_items), dtype=np.float32)
        np.add.at(occ, (users, items), 1.0)
        occ = (occ > 0).astype(np.float32)

        # (items, items) co-occurrence on the MXU
        cooccur = _jitted("sar.cooccur", lambda O: O.T @ O)
        C = np.asarray(cooccur(jnp.asarray(occ)))
        C = np.where(C >= self.get("support_threshold"), C, 0.0)
        diag = np.diag(C).copy()
        sim_kind = self.get("similarity_function")
        if sim_kind == "cooccurrence":
            S = C
        elif sim_kind == "lift":
            denom = np.outer(diag, diag)
            S = np.divide(C, denom, out=np.zeros_like(C), where=denom > 0)
        else:  # jaccard
            denom = diag[:, None] + diag[None, :] - C
            S = np.divide(C, denom, out=np.zeros_like(C), where=denom > 0)

        m = SARModel()
        m.set(user_col=self.get("user_col"), item_col=self.get("item_col"),
              rating_col=rcol or "rating",
              item_similarity=S.astype(np.float32),
              user_affinity=A)
        return m


class SARModel(Model):
    user_col = Param(str, default="user", doc="user id column")
    item_col = Param(str, default="item", doc="item id column")
    rating_col = Param(str, default="rating", doc="score output column")
    item_similarity = ComplexParam(default=None, doc="(items, items) matrix")
    user_affinity = ComplexParam(default=None, doc="(users, items) matrix")

    def _scores(self) -> np.ndarray:
        import jax.numpy as jnp

        run = _jitted("sar.affinity_matmul", lambda A, S: A @ S)
        return np.asarray(run(jnp.asarray(self.get("user_affinity")),
                              jnp.asarray(self.get("item_similarity"))))

    def _transform(self, df: DataFrame) -> DataFrame:
        """Score (user, item) pairs."""
        scores = self._scores()
        users = df[self.get("user_col")].astype(np.int64)
        items = df[self.get("item_col")].astype(np.int64)
        ok = (users < scores.shape[0]) & (items < scores.shape[1])
        vals = np.zeros(len(df))
        vals[ok] = scores[users[ok], items[ok]]
        return df.with_column("prediction", vals)

    def recommend_for_all_users(self, k: int = 10,
                                remove_seen: bool = True) -> DataFrame:
        """Top-k unseen items per user (reference SARModel.recommendForAllUsers)."""
        scores = self._scores().copy()
        A = np.asarray(self.get("user_affinity"))
        if remove_seen:
            scores[A > 0] = -np.inf
        k = min(k, scores.shape[1])
        top = np.argsort(-scores, axis=1)[:, :k]
        n_users = scores.shape[0]
        recs = np.empty(n_users, dtype=object)
        ratings = np.empty(n_users, dtype=object)
        for u in range(n_users):
            # seen items were masked to -inf; a user with < k unseen items
            # gets a shorter list rather than padded fake recommendations
            keep = [i for i in top[u] if np.isfinite(scores[u, i])]
            recs[u] = [int(i) for i in keep]
            ratings[u] = [float(scores[u, i]) for i in keep]
        return DataFrame({self.get("user_col"): np.arange(n_users),
                          "recommendations": recs, "ratings": ratings})
