"""Prometheus text exposition (format version 0.0.4) for the registry.

Stdlib-only renderer for `MetricsRegistry` — the serving plane returns
its output from ``GET /metrics``. Histogram buckets are rendered
cumulatively with an explicit ``+Inf`` bucket, ``_sum`` and ``_count``,
per the exposition spec.

When exemplars are enabled (``tracing.set_exemplars(True)`` installs a
registry-level provider), histogram bucket lines additionally carry the
OpenMetrics exemplar suffix ``# {trace_id="..."} value`` — the join key
from an aggregate latency bucket to the per-request span tree in the
flight recorder. With the provider unset (the default) the output is
byte-identical to plain 0.0.4 text.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

__all__ = ["CONTENT_TYPE", "render_prometheus"]


def _escape_help(s: str) -> str:
    return s.replace("\\", r"\\").replace("\n", r"\n")


def _escape_label(s: str) -> str:
    return s.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _fmt_value(v: float) -> str:
    f = float(v)
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    if f != f:  # NaN
        return "NaN"
    if f.is_integer() and abs(f) < 1e17:
        return str(int(f))
    return repr(f)


def _labelstr(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in labels.items()]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _exemplar_suffix(exemplars: Dict[int, Tuple[str, float]],
                     i: int) -> str:
    ex: Optional[Tuple[str, float]] = exemplars.get(i)
    if ex is None:
        return ""
    trace_id, value = ex
    return (f' # {{trace_id="{_escape_label(trace_id)}"}} '
            f"{_fmt_value(value)}")


def render_prometheus(registry) -> str:
    """Render every metric in `registry` as Prometheus text exposition."""
    from .registry import exemplar_provider
    with_exemplars = exemplar_provider() is not None
    lines = []
    for m in registry.metrics():
        lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        for labels, series in m.series():
            if m.kind == "histogram":
                counts, total, count = series.get()
                exemplars = series.exemplars() if with_exemplars else {}
                acc = 0
                for i, (upper, c) in enumerate(zip(m.buckets, counts)):
                    acc += c
                    le = f'le="{_fmt_value(upper)}"'
                    lines.append(f"{m.name}_bucket{_labelstr(labels, le)} "
                                 f"{acc}{_exemplar_suffix(exemplars, i)}")
                inf_le = 'le="+Inf"'
                lines.append(f"{m.name}_bucket{_labelstr(labels, inf_le)} "
                             f"{count}"
                             f"{_exemplar_suffix(exemplars, len(m.buckets))}")
                lines.append(f"{m.name}_sum{_labelstr(labels)} "
                             f"{_fmt_value(total)}")
                lines.append(f"{m.name}_count{_labelstr(labels)} {count}")
            else:
                lines.append(f"{m.name}{_labelstr(labels)} "
                             f"{_fmt_value(series.get())}")
    return "\n".join(lines) + "\n" if lines else ""
