"""SLO engine: rolling per-class scorecards over the serving plane.

ROADMAP item 5 asks for BENCH-style SLO scorecards (goodput, p50/p99/p999,
shed rate, error-budget burn) that feed the tuning ``ObservationStore`` so
the ``CostModel`` optimizes against traffic-shaped load. This module is
the measurement half: a process-global :class:`SloTracker` that every
request funnel (``WorkerServer._observe_request``, bench phases) reports
into, bucketed by **workload class** — the ``{transport, route, model,
tenant}`` label tuple (``tenant`` arrives via the optional
``X-Mmlspark-Tenant`` request header and defaults to ``"default"``).

Design constraints mirror the registry's (registry.py): pure stdlib,
default-on (one dict lookup + a few adds per request), process-global
(``get_tracker()``), resettable (``reset_tracker()``), and snapshot-able
(:meth:`SloTracker.scorecard` returns plain JSON served at
``GET /debug/slo`` and harvested by
``tuning.observations.harvest_scorecard`` as ``source="slo_scorecard"``
rows).

Two time scales per class, on purpose:

- **cumulative totals** (``total`` / ``errors_total`` / ``shed_total``)
  never decay — they reconcile exactly against
  ``mmlspark_serving_requests_total`` at ``/metrics``;
- a **rolling window** (``window_seconds``, default 60 s, split into
  ``num_buckets`` ring buckets) carries the live rate/latency view the
  burn-rate math runs on — stale buckets are recycled lazily on write,
  so an idle tracker costs nothing.

The latency sketch is the registry's fixed-bucket histogram shape
(``DEFAULT_LATENCY_BUCKETS`` uppers, quantiles interpolated within a
bucket) — no per-request list is ever kept, which is exactly why
hand-rolled ``sorted()[int(0.99*len)]`` windows elsewhere are a lint
finding (tpulint TPU011).
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from .registry import DEFAULT_LATENCY_BUCKETS
from .registry import counter as _metric_counter
from .registry import gauge as _metric_gauge

__all__ = ["DEFAULT_TENANT", "SloPolicy", "SloTracker", "classify_route",
           "get_tracker", "set_tracker", "reset_tracker"]

# the serving-plane SLO mirror: the same per-class counts the scorecard
# reports, visible to a plain /metrics scrape (docs/observability.md)
_M_SLO_REQUESTS = _metric_counter(
    "mmlspark_slo_requests_total",
    "Requests observed by the SLO tracker, by workload class",
    ("transport", "route", "model", "tenant"))
_M_SLO_ERRORS = _metric_counter(
    "mmlspark_slo_errors_total",
    "Observed requests that counted against the error budget (5xx)",
    ("transport", "route", "model", "tenant"))
_M_SLO_SHED = _metric_counter(
    "mmlspark_slo_shed_total",
    "Requests shed (429) per workload class — tracked apart from errors "
    "because shedding is load policy, not failure",
    ("transport", "route", "model", "tenant"))
_M_SLO_BURN = _metric_gauge(
    "mmlspark_slo_error_budget_burn",
    "Rolling-window error-budget burn rate per class (1.0 = burning "
    "exactly the budget; refreshed at scorecard time)",
    ("transport", "route", "model", "tenant"))
_M_SLO_P99 = _metric_gauge(
    "mmlspark_slo_p99_seconds",
    "Rolling-window p99 latency per class (refreshed at scorecard time)",
    ("transport", "route", "model", "tenant"))
_M_KV_QUANT = _metric_gauge(
    "mmlspark_kv_quant_error",
    "Latest sampled KV quantization error per model: relative RMS of "
    "dequantize(quantize(rows)) vs the bf16 oracle rows at write time "
    "(0 on unquantized engines; feeds the registry's canary check)",
    ("model",))

#: classes beyond this cap collapse into ("other", "other", "other",
#: "other") — a label-cardinality bound, same motivation as Prometheus
#: practice. The tenant dimension rides inside the same cap: a burst of
#: novel tenant strings lands in the overflow class, not the label space.
MAX_CLASSES = 64
_OVERFLOW_KEY = ("other", "other", "other", "other")
DEFAULT_TENANT = "default"


class SloPolicy:
    """Service objectives the scorecard judges each class against.

    ``target_p99`` — seconds; the window p99 at or under this passes.
    ``availability`` — success-ratio objective in (0, 1); its complement
    is the error budget the burn rate is normalized by (burn 1.0 = errors
    arriving at exactly the budgeted rate; >1 exhausts the budget early).
    """

    __slots__ = ("target_p99", "availability")

    def __init__(self, target_p99: float = 0.5,
                 availability: float = 0.999):
        if not 0.0 < availability < 1.0:
            raise ValueError("availability must be in (0, 1)")
        if target_p99 <= 0.0:
            raise ValueError("target_p99 must be positive")
        self.target_p99 = float(target_p99)
        self.availability = float(availability)

    def as_dict(self) -> Dict[str, float]:
        return {"target_p99": self.target_p99,
                "availability": self.availability}


def classify_route(path: Optional[str]) -> str:
    """Collapse a request path to a bounded route class.

    The scorecard is per *workload class*, not per URL — unbounded label
    sets would blow up both the tracker and the mirrored metrics."""
    if not path:
        return "api"
    path = path.partition("?")[0]
    if path.startswith("/healthz"):
        return "healthz"
    if path.startswith("/metrics"):
        return "metrics"
    if path.startswith("/debug"):
        return "debug"
    return "api"


class _WinBucket:
    """One ring slot: counts + a fixed-bucket latency sketch."""

    __slots__ = ("epoch", "count", "errors", "shed", "lat_counts",
                 "lat_sum")

    def __init__(self, n_lat: int):
        self.epoch = -1
        self.count = 0
        self.errors = 0
        self.shed = 0
        self.lat_counts = [0] * n_lat
        self.lat_sum = 0.0

    def reset(self, epoch: int) -> None:
        self.epoch = epoch
        self.count = self.errors = self.shed = 0
        for i in range(len(self.lat_counts)):
            self.lat_counts[i] = 0
        self.lat_sum = 0.0


class _Class:
    """Per-workload-class state: cumulative totals + the bucket ring."""

    __slots__ = ("total", "errors_total", "shed_total", "ring")

    def __init__(self, num_buckets: int, n_lat: int):
        self.total = 0
        self.errors_total = 0
        self.shed_total = 0
        self.ring = [_WinBucket(n_lat) for _ in range(num_buckets)]


class SloTracker:
    """Time-bucketed rolling SLO windows per ``{transport, route, model,
    tenant}``.

    ``clock`` is injectable (monotonic seconds) so tests drive window
    rotation deterministically. All mutation is under one lock — the
    per-request cost is a dict lookup plus a handful of integer adds.
    """

    def __init__(self, policy: Optional[SloPolicy] = None,
                 window_seconds: float = 60.0, num_buckets: int = 12,
                 clock: Callable[[], float] = time.monotonic,
                 max_classes: int = MAX_CLASSES):
        if window_seconds <= 0 or num_buckets < 1:
            raise ValueError("window_seconds and num_buckets must be "
                             "positive")
        self.policy = policy or SloPolicy()
        self.window_seconds = float(window_seconds)
        self.num_buckets = int(num_buckets)
        self._width = self.window_seconds / self.num_buckets
        self._clock = clock
        self._max_classes = int(max_classes)
        self._uppers: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS
        self._lock = threading.Lock()
        self._classes: Dict[Tuple[str, str, str, str], _Class] = {}
        # model -> ring of [epoch, sum, count, max] KV quant-error
        # samples (same epoch math as the request ring; bounded by
        # max_classes like everything else label-shaped)
        self._quant: Dict[str, List[List[float]]] = {}

    # -- recording -----------------------------------------------------------
    def _class(self, transport: str, route: str, model: str,
               tenant: str) -> _Class:
        key = (str(transport), str(route), str(model), str(tenant))
        cls = self._classes.get(key)
        if cls is None:
            if len(self._classes) >= self._max_classes:
                key = _OVERFLOW_KEY
                cls = self._classes.get(key)
                if cls is not None:
                    return cls
            cls = self._classes[key] = _Class(self.num_buckets,
                                              len(self._uppers) + 1)
        return cls

    def _bucket(self, cls: _Class) -> _WinBucket:
        epoch = int(self._clock() / self._width)
        b = cls.ring[epoch % self.num_buckets]
        if b.epoch != epoch:
            b.reset(epoch)
        return b

    def observe(self, transport: str = "api", route: str = "api",
                model: str = "default",
                seconds: Optional[float] = None,
                error: bool = False,
                tenant: str = DEFAULT_TENANT) -> None:
        """One answered request. ``seconds`` feeds the latency sketch when
        known; ``error=True`` charges the class's error budget (5xx —
        sheds go through :meth:`shed` instead)."""
        with self._lock:
            cls = self._class(transport, route, model, tenant)
            b = self._bucket(cls)
            cls.total += 1
            b.count += 1
            if error:
                cls.errors_total += 1
                b.errors += 1
            if seconds is not None:
                i = bisect.bisect_left(self._uppers, seconds)
                b.lat_counts[i] += 1
                b.lat_sum += seconds
        _M_SLO_REQUESTS.inc(transport=transport, route=route, model=model,
                            tenant=tenant)
        if error:
            _M_SLO_ERRORS.inc(transport=transport, route=route,
                              model=model, tenant=tenant)

    def shed(self, transport: str = "api", route: str = "api",
             model: str = "default", tenant: str = DEFAULT_TENANT) -> None:
        """One request refused by admission control (429)."""
        with self._lock:
            cls = self._class(transport, route, model, tenant)
            b = self._bucket(cls)
            cls.shed_total += 1
            b.shed += 1
        _M_SLO_SHED.inc(transport=transport, route=route, model=model,
                        tenant=tenant)

    def note_kv_quant_error(self, model: str, rms: float) -> None:
        """One sampled KV quantization-error observation for ``model``
        (the engine's write-time oracle probe — relative RMS of the
        quantize/dequantize roundtrip vs the bf16 rows). Rolls through
        the same window ring as request stats so
        :meth:`model_window`'s ``kv_quant_error`` and a canary's
        latency/error view cover the same period."""
        model = str(model)
        rms = float(rms)
        with self._lock:
            ring = self._quant.get(model)
            if ring is None:
                if len(self._quant) >= self._max_classes:
                    model = "other"
                    ring = self._quant.get(model)
                if ring is None:
                    ring = self._quant[model] = [
                        [-1, 0.0, 0, 0.0] for _ in range(self.num_buckets)]
            epoch = int(self._clock() / self._width)
            b = ring[epoch % self.num_buckets]
            if b[0] != epoch:
                b[0], b[1], b[2], b[3] = epoch, 0.0, 0, 0.0
            b[1] += rms
            b[2] += 1
            b[3] = max(b[3], rms)
        _M_KV_QUANT.set(rms, model=model)

    def _quant_window(self, model: str) -> Dict[str, object]:
        """Merged live-window quant-error stats for ``model`` (caller
        holds the lock). ``mean`` is None when nothing was sampled."""
        ring = self._quant.get(str(model))
        out = {"count": 0, "mean": None, "max": None}
        if ring is None:
            return out
        now_epoch = int(self._clock() / self._width)
        total, n, mx = 0.0, 0, 0.0
        for b in ring:
            if b[0] < 0 or now_epoch - b[0] >= self.num_buckets:
                continue
            total += b[1]
            n += b[2]
            mx = max(mx, b[3])
        if n:
            out = {"count": n, "mean": total / n, "max": mx}
        return out

    # -- reading -------------------------------------------------------------
    def _window_view(self, cls: _Class) -> Tuple[int, int, int, List[int],
                                                 float]:
        """Merge the ring's LIVE buckets (epoch within the window)."""
        now_epoch = int(self._clock() / self._width)
        count = errors = shed = 0
        lat = [0] * (len(self._uppers) + 1)
        lat_sum = 0.0
        for b in cls.ring:
            if b.epoch < 0 or now_epoch - b.epoch >= self.num_buckets:
                continue
            count += b.count
            errors += b.errors
            shed += b.shed
            lat_sum += b.lat_sum
            for i, c in enumerate(b.lat_counts):
                lat[i] += c
        return count, errors, shed, lat, lat_sum

    def _quantile(self, lat: List[int], q: float) -> Optional[float]:
        total = sum(lat)
        if total == 0:
            return None
        rank = q * total
        acc = 0
        for i, c in enumerate(lat):
            if c == 0:
                continue
            prev_acc = acc
            acc += c
            if acc >= rank:
                if i >= len(self._uppers):
                    # +Inf bucket: the last finite boundary is the best
                    # honest answer a fixed sketch can give
                    return self._uppers[-1]
                lo = self._uppers[i - 1] if i > 0 else 0.0
                hi = self._uppers[i]
                frac = (rank - prev_acc) / c
                return lo + (hi - lo) * min(1.0, max(0.0, frac))
        return self._uppers[-1]

    def burn_rate(self, transport: str, route: str,
                  model: str = "default",
                  tenant: str = DEFAULT_TENANT) -> float:
        """Window error rate over the policy's error budget: 1.0 means
        errors arrive at exactly the budgeted rate, >1 exhausts the
        budget early. 0.0 on an idle window."""
        with self._lock:
            cls = self._classes.get((str(transport), str(route),
                                     str(model), str(tenant)))
            if cls is None:
                return 0.0
            count, errors, _, _, _ = self._window_view(cls)
        if count == 0:
            return 0.0
        budget = 1.0 - self.policy.availability
        return (errors / count) / budget

    def model_window(self, model: str) -> Dict[str, object]:
        """Rolling-window stats aggregated across every class whose
        ``model`` dimension matches — the canary-governance read: the
        model registry compares a candidate version's window (model =
        ``name@candidate``) against its incumbent's, regardless of which
        transports/routes/tenants the traffic arrived on."""
        with self._lock:
            views = [self._window_view(cls)
                     for key, cls in self._classes.items()
                     if key[2] == str(model)]
            quant = self._quant_window(model)
        count = sum(v[0] for v in views)
        errors = sum(v[1] for v in views)
        lat = [0] * (len(self._uppers) + 1)
        for v in views:
            for i, c in enumerate(v[3]):
                lat[i] += c
        return {"model": str(model), "count": count, "errors": errors,
                "error_rate": (errors / count) if count else 0.0,
                "p99": self._quantile(lat, 0.99),
                "kv_quant_error": quant["mean"],
                "kv_quant_samples": quant["count"]}

    def scorecard(self) -> Dict[str, object]:
        """JSON-safe rolling scorecard over every workload class.

        Per class: cumulative ``total``/``errors_total``/``shed_total``
        (reconcile against ``mmlspark_serving_requests_total``), the live
        ``window`` rates, interpolated p50/p99/p999 from the latency
        sketch, availability, burn rate, and the pass/fail verdicts
        against :class:`SloPolicy`. Also refreshes the
        ``mmlspark_slo_error_budget_burn`` / ``mmlspark_slo_p99_seconds``
        gauges so scrapes and scorecards agree."""
        with self._lock:
            items = sorted(self._classes.items())
            views = [(key, cls.total, cls.errors_total, cls.shed_total,
                      self._window_view(cls)) for key, cls in items]
            kv_quant = {m: self._quant_window(m)
                        for m in sorted(self._quant)}
        budget = 1.0 - self.policy.availability
        classes: List[Dict[str, object]] = []
        for (transport, route, model, tenant), total, errors_total, \
                shed_total, (count, errors, shed, lat, lat_sum) in views:
            p50 = self._quantile(lat, 0.50)
            p99 = self._quantile(lat, 0.99)
            p999 = self._quantile(lat, 0.999)
            availability = (1.0 - errors / count) if count else None
            burn = (errors / count) / budget if count else 0.0
            labels = dict(transport=transport, route=route, model=model,
                          tenant=tenant)
            _M_SLO_BURN.set(burn, **labels)
            _M_SLO_P99.set(p99 if p99 is not None else 0.0, **labels)
            classes.append({
                "transport": transport, "route": route, "model": model,
                "tenant": tenant,
                "total": total, "errors_total": errors_total,
                "shed_total": shed_total,
                "window": {
                    "count": count, "errors": errors, "shed": shed,
                    "rps": round(count / self.window_seconds, 4),
                    "latency_sum": round(lat_sum, 6)},
                "p50": p50, "p99": p99, "p999": p999,
                "availability": availability,
                "error_budget_burn": round(burn, 4),
                "p99_ok": (None if p99 is None
                           else bool(p99 <= self.policy.target_p99)),
                "availability_ok": (None if availability is None
                                    else bool(availability
                                              >= self.policy.availability)),
            })
        return {"t": time.time(),
                "window_seconds": self.window_seconds,
                "num_buckets": self.num_buckets,
                "policy": self.policy.as_dict(),
                "classes": classes,
                "kv_quant": kv_quant}

    def reset(self) -> None:
        with self._lock:
            self._classes.clear()
            self._quant.clear()


# -- the process-global tracker ----------------------------------------------

_tracker_lock = threading.Lock()
_tracker: Optional[SloTracker] = None


def get_tracker() -> SloTracker:
    """The process-global tracker, created on first use (default policy,
    60 s window) — the one ``WorkerServer`` and bench.py report into."""
    global _tracker
    with _tracker_lock:
        if _tracker is None:
            _tracker = SloTracker()
        return _tracker


def set_tracker(tracker: Optional[SloTracker]) -> None:
    """Install a specific tracker (tests, custom policies)."""
    global _tracker
    with _tracker_lock:
        _tracker = tracker


def reset_tracker() -> None:
    """Drop the global tracker (test hook — pair with
    ``observability.reset_all`` to zero the mirrored metric series)."""
    set_tracker(None)
