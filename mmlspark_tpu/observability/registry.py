"""Process-global metrics registry: Counter, Gauge, Histogram with labels.

The unified telemetry substrate for the whole package — `StageCounters`
(ops/compile_cache.py), `_PhaseProf` (models/gbdt/train.py) and
`SpanTracer` (utils/profiling.py) all mirror into it, and the serving
plane scrapes it at ``GET /metrics`` (see serving/server.py). Design
constraints, in order:

- **pure stdlib** — no prometheus_client; the container has no network.
- **default-on** — an update on a cached series is one small lock plus a
  float add (~100 ns); nothing here may touch jax, numpy or I/O.
- **process-global** — one registry per process (`get_registry()`), so a
  metric registered at import time in ops/ is visible to a scrape served
  from serving/ without any plumbing.
- **resettable** — tests call `reset_all()`; metric *objects* held by
  modules stay valid (only their series are cleared), so import-time
  registration and per-test isolation coexist.
- **snapshot-able** — `snapshot()` returns a plain JSON-safe dict for
  bench.py's one-shot reporter; `render()` returns Prometheus text.
"""

from __future__ import annotations

import bisect
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "DEFAULT_LATENCY_BUCKETS",
    "get_registry",
    "counter",
    "gauge",
    "histogram",
    "snapshot",
    "render",
    "reset_all",
    "set_exemplar_provider",
    "exemplar_provider",
    "build_info",
    "process_uptime_seconds",
]

#: Wall-clock at first observability import — the process-uptime epoch
#: reported by /healthz (observability is imported at package import, so
#: this tracks process age for any consumer of the package).
_PROCESS_START = time.time()

#: When set (tracing.set_exemplars), histogram observations call this to
#: capture the active trace_id as an OpenMetrics exemplar. None (the
#: default) keeps observe() exemplar-free and the exposition byte-identical
#: to plain Prometheus 0.0.4 text.
_EXEMPLAR_PROVIDER: Optional[Callable[[], Optional[str]]] = None


def set_exemplar_provider(
        fn: Optional[Callable[[], Optional[str]]]) -> None:
    global _EXEMPLAR_PROVIDER
    _EXEMPLAR_PROVIDER = fn


def exemplar_provider() -> Optional[Callable[[], Optional[str]]]:
    return _EXEMPLAR_PROVIDER


def process_uptime_seconds() -> float:
    return time.time() - _PROCESS_START

#: Default histogram boundaries, tuned for batch-inference latencies: the
#: sub-millisecond region resolves per-stage host work (coerce/pad), the
#: 1 ms – 1 s region resolves dispatch + drain, and the long tail covers
#: inline XLA compiles (multi-second for real models).
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

_INF = float("inf")


def _validate_name(name: str) -> None:
    if not name or not all(c.isalnum() or c in "_:" for c in name) \
            or name[0].isdigit():
        raise ValueError(f"invalid metric name {name!r}")


class _CounterSeries:
    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    def get(self) -> float:
        with self._lock:
            return self._value


class _GaugeSeries:
    __slots__ = ("_lock", "_value", "_fn")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float) -> None:
        with self._lock:
            self._fn = None
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        with self._lock:
            self._fn = fn

    def get(self) -> float:
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        try:
            return float(fn())
        except Exception:
            return 0.0


class _HistogramSeries:
    __slots__ = ("_lock", "_uppers", "_counts", "_sum", "_count",
                 "_exemplars")

    def __init__(self, uppers: Tuple[float, ...]) -> None:
        self._lock = threading.Lock()
        self._uppers = uppers
        self._counts = [0] * (len(uppers) + 1)  # last slot is +Inf
        self._sum = 0.0
        self._count = 0
        #: bucket index → (trace_id, observed value); lazily allocated so
        #: the exemplar-free hot path stays two attribute reads
        self._exemplars: Optional[Dict[int, Tuple[str, float]]] = None

    def observe(self, value: float) -> None:
        # le is inclusive: a value equal to a boundary lands in that bucket
        i = bisect.bisect_left(self._uppers, value)
        provider = _EXEMPLAR_PROVIDER
        trace_id = provider() if provider is not None else None
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1
            if trace_id is not None:
                if self._exemplars is None:
                    self._exemplars = {}
                self._exemplars[i] = (trace_id, value)

    def get(self) -> Tuple[List[int], float, int]:
        with self._lock:
            return list(self._counts), self._sum, self._count

    def exemplars(self) -> Dict[int, Tuple[str, float]]:
        """Last-observed exemplar per bucket index (+Inf = len(uppers))."""
        with self._lock:
            return dict(self._exemplars) if self._exemplars else {}


class _Metric:
    """Shared label-set machinery; subclasses define the series type."""

    kind = ""

    def __init__(self, name: str, help: str,
                 labelnames: Sequence[str] = ()) -> None:
        _validate_name(name)
        for ln in labelnames:
            _validate_name(ln)
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            # unlabeled metrics expose their single series immediately (at
            # zero), matching prometheus_client — so e.g. cache-miss
            # counters appear in /metrics before the first miss
            self._series[()] = self._new_series()

    def _new_series(self):
        raise NotImplementedError

    def labels(self, **labels: object):
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: got labels {sorted(labels)}, "
                f"declared {sorted(self.labelnames)}")
        key = tuple(str(labels[n]) for n in self.labelnames)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = self._new_series()
        return series

    def remove(self, **labels: object) -> None:
        """Drop one labeled series (e.g. a closed server's gauges)."""
        key = tuple(str(labels[n]) for n in self.labelnames)
        with self._lock:
            self._series.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self._series.clear()
            if not self.labelnames:
                self._series[()] = self._new_series()

    def series(self) -> List[Tuple[Dict[str, str], object]]:
        """[(labels-dict, series)] in insertion order, snapshotted."""
        with self._lock:
            items = list(self._series.items())
        return [(dict(zip(self.labelnames, key)), s) for key, s in items]


class Counter(_Metric):
    kind = "counter"

    def _new_series(self) -> _CounterSeries:
        return _CounterSeries()

    def inc(self, amount: float = 1.0, **labels: object) -> None:
        self.labels(**labels).inc(amount)


class Gauge(_Metric):
    kind = "gauge"

    def _new_series(self) -> _GaugeSeries:
        return _GaugeSeries()

    def set(self, value: float, **labels: object) -> None:
        self.labels(**labels).set(value)

    def inc(self, amount: float = 1.0, **labels: object) -> None:
        self.labels(**labels).inc(amount)

    def dec(self, amount: float = 1.0, **labels: object) -> None:
        self.labels(**labels).dec(amount)

    def set_function(self, fn: Callable[[], float],
                     **labels: object) -> None:
        """Sample ``fn()`` at collection time (queue depths, pool sizes)."""
        self.labels(**labels).set_function(fn)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str,
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS) -> None:
        uppers = tuple(float(b) for b in buckets if b != _INF)
        if not uppers or list(uppers) != sorted(set(uppers)):
            raise ValueError(
                f"{name}: buckets must be sorted, unique and non-empty")
        self.buckets = uppers  # +Inf is implicit
        super().__init__(name, help, labelnames)

    def _new_series(self) -> _HistogramSeries:
        return _HistogramSeries(self.buckets)

    def observe(self, value: float, **labels: object) -> None:
        self.labels(**labels).observe(value)

    def time(self, **labels: object) -> "_HistogramTimer":
        return _HistogramTimer(self.labels(**labels))


class _HistogramTimer:
    """``with hist.time(): ...`` — observes elapsed wall-clock on exit."""

    __slots__ = ("_series", "_t0")

    def __init__(self, series: _HistogramSeries) -> None:
        self._series = series
        self._t0 = 0.0

    def __enter__(self) -> "_HistogramTimer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc: object) -> None:
        self._series.observe(time.perf_counter() - self._t0)


class MetricsRegistry:
    """Name → metric map; get-or-create with type/label-set checking."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Sequence[str], **kwargs) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, labelnames,
                                              **kwargs)
                return m
        if type(m) is not cls:
            raise ValueError(
                f"{name} already registered as {m.kind}, not {cls.kind}")
        if m.labelnames != tuple(labelnames):
            raise ValueError(
                f"{name} already registered with labels {m.labelnames}, "
                f"not {tuple(labelnames)}")
        return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
                  ) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def snapshot(self) -> Dict[str, dict]:
        """JSON-safe dict of every series — bench.py embeds this verbatim.

        Histogram ``buckets`` are cumulative (same le semantics as the
        Prometheus exposition); the key of the overflow bucket is "+Inf".
        """
        out: Dict[str, dict] = {}
        for m in self.metrics():
            series = []
            for labels, s in m.series():
                if isinstance(s, _HistogramSeries):
                    counts, total, count = s.get()
                    acc, buckets = 0, {}
                    for upper, c in zip(m.buckets, counts):
                        acc += c
                        buckets[repr(upper)] = acc
                    buckets["+Inf"] = count
                    series.append({"labels": labels, "sum": total,
                                   "count": count, "buckets": buckets})
                else:
                    series.append({"labels": labels, "value": s.get()})
            out[m.name] = {"type": m.kind, "help": m.help, "series": series}
        return out

    def render(self) -> str:
        from .exposition import render_prometheus
        return render_prometheus(self)

    def reset(self) -> None:
        """Zero every series; registered metric objects stay valid."""
        for m in self.metrics():
            m.clear()


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY


def counter(name: str, help: str = "",
            labelnames: Sequence[str] = ()) -> Counter:
    return _REGISTRY.counter(name, help, labelnames)


def gauge(name: str, help: str = "",
          labelnames: Sequence[str] = ()) -> Gauge:
    return _REGISTRY.gauge(name, help, labelnames)


def histogram(name: str, help: str = "",
              labelnames: Sequence[str] = (),
              buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
              ) -> Histogram:
    return _REGISTRY.histogram(name, help, labelnames, buckets)


def snapshot() -> Dict[str, dict]:
    return _REGISTRY.snapshot()


def render() -> str:
    return _REGISTRY.render()


def reset_all() -> None:
    _REGISTRY.reset()


def build_info() -> Gauge:
    """Register/refresh the ``mmlspark_build_info`` identity gauge.

    The standard *_build_info idiom: value 1, identity in the labels
    (package version, jax version, jax backend) — scrapes can tell which
    build and runtime they hit. jax is reported only if something else
    already imported it (``sys.modules`` probe), and the backend only if
    the runtime already initialized one: this function must never trigger
    jax import or — worse — backend/TPU initialization (a WorkerServer
    built in a jax-free process would otherwise stall ~30 s on the TPU
    metadata probe).
    """
    version = jax_version = backend = "unknown"
    try:
        from .. import __version__ as version
    except Exception:
        pass
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        jax_version = getattr(jax_mod, "__version__", "unknown")
        try:
            from jax._src import xla_bridge as _xb
            if _xb.backends_are_initialized():
                backend = jax_mod.default_backend()
        except Exception:
            pass
    g = gauge("mmlspark_build_info",
              "Build/runtime identity (value is always 1; the labels carry "
              "the information)", ("version", "jax", "backend"))
    g.set(1, version=version, jax=jax_version, backend=backend)
    return g
