"""Time-series plane: fixed-memory metric history, trend queries, alerts.

Every measurement surface built so far (``/debug/slo``, ``/debug/costs``,
``/debug/cluster``, the scenario scorecards) is an instantaneous snapshot
or an end-of-run aggregate; nothing records how a signal *moved*. This
module is the missing history plane — the sensor half of ROADMAP item 4's
``FleetController`` (a control loop over "sustained burn-rate/queue-depth
pressure" needs trajectories, not point samples):

- :class:`TimeSeriesStore` — process-global, **fixed-memory** history.
  Each named series holds one preallocated ring (``array`` columns, no
  per-sample allocation) per downsample tier — default
  ``1s×120 → 10s×180 → 60s×120`` (:data:`DEFAULT_TIERS`, overridable via
  ``MMLSPARK_TPU_TS_TIERS="1x120,10x180,60x120"``). Every tier ingests
  every sample, so a coarse bucket carries exact min/max/mean/last for its
  span — a 100 ms latency spike survives into the 60 s tier instead of
  being averaged away. Series count is capped (``max_series``, drops
  counted in ``mmlspark_timeseries_dropped_total``), which makes the
  store's memory bound a compile-time product:
  ``max_series × Σ slots × 6 doubles`` (:meth:`TimeSeriesStore.byte_budget`).
- :class:`RegistrySampler` — background thread that scrapes the
  ``MetricsRegistry`` every ``MMLSPARK_TPU_TS_INTERVAL`` seconds
  (default 1.0; ``<= 0`` disables the thread, ``tick()`` stays callable
  for tests). Counters become per-second **rates** with the federation
  plane's reset protection (``_CounterState``), gauges are sampled
  directly, histograms are reduced to per-interval ``:p50``/``:p99``
  via the registry sketch's linear-interpolation quantile (slo.py's
  ``_quantile`` shape). Extra callables can be attached with
  :meth:`RegistrySampler.add_source` (the serving plane feeds per-port
  queue saturation and drain rate this way). The worker-side sampler is
  refcounted — every :class:`~mmlspark_tpu.serving.server.WorkerServer`
  acquires it on construction and releases it on ``close()``.
- :class:`ClusterSampler` — the driver-side variant: no thread, fed from
  federation heartbeats at ``DriverRegistry.heartbeat``'s observation
  point, so cluster-level series (per-worker queue depth / in-flight /
  HBM in use from the health digest, merged goodput and error-budget
  burn rate from the aggregator scorecard) accrue where ``/debug/cluster``
  is served.
- Query API — :meth:`~TimeSeriesStore.range`,
  :meth:`~TimeSeriesStore.rate` (counter-reset tolerant),
  :meth:`~TimeSeriesStore.ewma`, and
  :meth:`~TimeSeriesStore.sustained` (predicate held across the whole
  window — the primitive the alert engine evaluates). Served at
  ``GET /debug/timeseries`` on both transports as JSON, or as a terminal
  sparkline view with ``?format=text`` (:func:`render_sparklines`).
- :class:`AlertEngine` — :class:`AlertRule` predicates with hysteresis:
  a rule **fires** only after its predicate holds for ``for_seconds``
  (sustained, not instantaneous — one bad sample never pages) and
  **resolves** only after the latest bucket has been good continuously
  for ``keep_firing_seconds`` — so a signal oscillating at the threshold
  cannot flap the rule. Transitions bump
  ``mmlspark_alerts_firing{rule}`` / ``mmlspark_alert_transitions_total
  {rule,to}``, land in the event log, and run ``on_fire`` hooks; the
  default hook drops a watchdog-style atomic JSON bundle (tmp +
  ``os.replace`` under the watchdog diag dir) with the offending series'
  recent window embedded. :func:`default_alert_rules` covers burn-rate,
  queue saturation, breaker flapping, and KV quantization error;
  ``MMLSPARK_TPU_ALERT_RULES`` adds or overrides rules with a
  ``name:series:op:threshold[:for=S][:keep=S][:field=F]`` grammar.

Pure stdlib, importable before jax, resettable for tests
(``reset_store()`` / ``reset_alert_engine()``) — same design constraints
as registry.py. Clocks are injectable everywhere (``time.monotonic``
default), which is what makes the hysteresis tests deterministic.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from array import array
from typing import (Callable, Dict, Iterable, List, Optional, Sequence,
                    Tuple)

from ..reliability.lock_sanitizer import new_lock as _new_lock
from .events import log_event
from .federation import _CounterState
from .registry import counter as _metric_counter
from .registry import gauge as _metric_gauge
from .registry import snapshot as _registry_snapshot

__all__ = [
    "AlertEngine",
    "AlertRule",
    "ClusterSampler",
    "DEFAULT_TIERS",
    "INTERVAL_ENV",
    "RULES_ENV",
    "TIERS_ENV",
    "RegistrySampler",
    "TimeSeriesStore",
    "acquire_sampler",
    "default_alert_rules",
    "get_alert_engine",
    "get_sampler",
    "get_store",
    "parse_alert_rules",
    "parse_tiers",
    "release_sampler",
    "render_sparklines",
    "reset_alert_engine",
    "reset_store",
    "sample_interval",
    "set_alert_engine",
    "set_store",
]

INTERVAL_ENV = "MMLSPARK_TPU_TS_INTERVAL"
TIERS_ENV = "MMLSPARK_TPU_TS_TIERS"
RULES_ENV = "MMLSPARK_TPU_ALERT_RULES"

# finest-first; each tier ingests every sample, so coarse buckets carry
# exact min/max/sum/count/last for their span (spikes survive downsampling)
DEFAULT_TIERS: Tuple[Tuple[float, int], ...] = (
    (1.0, 120), (10.0, 180), (60.0, 120))
DEFAULT_MAX_SERIES = 256
_STATS_PER_BUCKET = 6  # epoch, min, max, sum, count, last

_M_ALERTS_FIRING = _metric_gauge(
    "mmlspark_alerts_firing",
    "1 while the named alert rule is in its firing state", ("rule",))
_M_ALERT_TRANSITIONS = _metric_counter(
    "mmlspark_alert_transitions_total",
    "Alert rule lifecycle transitions", ("rule", "to"))
_M_TS_SERIES = _metric_gauge(
    "mmlspark_timeseries_series",
    "Live series held by the process-global time-series store")
_M_TS_SAMPLES = _metric_counter(
    "mmlspark_timeseries_samples_total",
    "Samples recorded into the process-global time-series store")
_M_TS_DROPPED = _metric_counter(
    "mmlspark_timeseries_dropped_total",
    "Samples dropped because the store's series cap was reached")


def sample_interval() -> float:
    """Registry-sampler period in seconds; ``<= 0`` disables the thread."""
    raw = os.environ.get(INTERVAL_ENV, "").strip()
    if not raw:
        return 1.0
    try:
        return float(raw)
    except ValueError:
        return 1.0


def parse_tiers(spec: Optional[str] = None) -> Tuple[Tuple[float, int], ...]:
    """Parse a ``"1x120,10x180,60x120"`` tier spec (width_s × slots).

    Falls back to :data:`DEFAULT_TIERS` on any malformed input — a bad
    env var degrades to the default shape rather than crashing a server.
    """
    if spec is None:
        spec = os.environ.get(TIERS_ENV, "")
    tiers: List[Tuple[float, int]] = []
    for part in spec.split(","):
        part = part.strip().lower()
        if not part:
            continue
        width, _, slots = part.partition("x")
        try:
            w, n = float(width), int(slots)
        except ValueError:
            return DEFAULT_TIERS
        if w <= 0 or n <= 0:
            return DEFAULT_TIERS
        tiers.append((w, n))
    if not tiers:
        return DEFAULT_TIERS
    tiers.sort()
    return tuple(tiers)


def _quantile_from_counts(uppers: Sequence[float], counts: Sequence[float],
                          total: float, q: float) -> float:
    """Interpolated quantile from per-bucket (non-cumulative) counts.

    Same shape as slo.py's ``_quantile``: linear interpolation inside the
    bucket that crosses the target rank; the +Inf bucket answers with the
    last finite boundary (the sketch cannot see past it).
    """
    if total <= 0:
        return 0.0
    target = q * total
    acc = 0.0
    lo = 0.0
    for upper, c in zip(uppers, counts):
        if c > 0:
            if acc + c >= target:
                if math.isinf(upper):
                    return lo
                return lo + (upper - lo) * ((target - acc) / c)
            acc += c
        if not math.isinf(upper):
            lo = upper
    return lo


class _Ring:
    """One downsample tier: a preallocated epoch-indexed stat ring.

    Bucket ``i = epoch % slots`` is lazily recycled when a newer epoch
    lands on it (same idiom as slo.py's window ring) — feeding is O(1)
    and the ring never allocates after construction.
    """

    __slots__ = ("width", "slots", "_epoch", "_min", "_max", "_sum",
                 "_count", "_last")

    def __init__(self, width: float, slots: int):
        self.width = float(width)
        self.slots = int(slots)
        self._epoch = array("q", [-(2 ** 62)] * self.slots)
        self._min = array("d", bytes(8 * self.slots))
        self._max = array("d", bytes(8 * self.slots))
        self._sum = array("d", bytes(8 * self.slots))
        self._count = array("d", bytes(8 * self.slots))
        self._last = array("d", bytes(8 * self.slots))

    def feed(self, t: float, value: float) -> None:
        e = int(t // self.width)
        i = e % self.slots
        if self._epoch[i] != e:
            self._epoch[i] = e
            self._min[i] = self._max[i] = self._last[i] = value
            self._sum[i] = value
            self._count[i] = 1.0
            return
        if value < self._min[i]:
            self._min[i] = value
        if value > self._max[i]:
            self._max[i] = value
        self._sum[i] += value
        self._count[i] += 1.0
        self._last[i] = value

    def buckets(self, now: float, seconds: float,
                ) -> List[Tuple[int, float, float, float, float, float]]:
        """``(epoch, min, max, sum, count, last)`` rows covering the
        trailing window, oldest first; empty epochs are omitted. The
        range starts at the epoch *containing* ``now - seconds`` (clamped
        to the ring span), so window-start coverage is answerable."""
        e_hi = int(now // self.width)
        e_lo = max(int((now - seconds) // self.width),
                   e_hi - self.slots + 1)
        out = []
        for e in range(e_lo, e_hi + 1):
            i = e % self.slots
            if self._epoch[i] == e and self._count[i] > 0:
                out.append((e, self._min[i], self._max[i], self._sum[i],
                            self._count[i], self._last[i]))
        return out


class _Series:
    __slots__ = ("name", "labels", "kind", "rings", "first_t", "last_t",
                 "last_value")

    def __init__(self, name: str, labels: Dict[str, str], kind: str,
                 tiers: Sequence[Tuple[float, int]]):
        self.name = name
        self.labels = labels
        self.kind = kind
        self.rings = [_Ring(w, n) for w, n in tiers]
        self.first_t: Optional[float] = None
        self.last_t: Optional[float] = None
        self.last_value = 0.0

    def feed(self, t: float, value: float) -> None:
        for ring in self.rings:
            ring.feed(t, value)
        if self.first_t is None:
            self.first_t = t
        self.last_t = t
        self.last_value = value


def _label_key(labels: Optional[Dict[str, object]],
               ) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class TimeSeriesStore:
    """Fixed-memory history of named (optionally labeled) series.

    Memory is bounded by construction: at most ``max_series`` series,
    each a fixed set of preallocated rings — no per-sample allocation,
    no growth with run length. ``byte_budget()`` is the provable upper
    bound; ``approx_bytes()`` the current estimate (tests assert the
    latter stays flat under a long synthetic run).
    """

    def __init__(self, tiers: Optional[Sequence[Tuple[float, int]]] = None,
                 *, clock: Callable[[], float] = time.monotonic,
                 max_series: int = DEFAULT_MAX_SERIES):
        self.tiers = parse_tiers() if tiers is None else tuple(
            (float(w), int(n)) for w, n in tiers)
        self.clock = clock
        self.max_series = int(max_series)
        self._lock = _new_lock("observability.timeseries.TimeSeriesStore")
        self._series: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                           _Series] = {}
        self._samples = 0
        self._dropped = 0

    # -- ingest ---------------------------------------------------------------

    def record(self, name: str, value: object,
               labels: Optional[Dict[str, object]] = None, *,
               t: Optional[float] = None, kind: str = "gauge") -> bool:
        """Feed one sample; False when dropped (cap or non-finite)."""
        try:
            v = float(value)  # type: ignore[arg-type]
        except (TypeError, ValueError):
            return False
        if not math.isfinite(v):
            return False
        if t is None:
            t = self.clock()
        key = (str(name), _label_key(labels))
        with self._lock:
            series = self._series.get(key)
            if series is None:
                if len(self._series) >= self.max_series:
                    self._dropped += 1
                    dropped = True
                else:
                    series = _Series(key[0], dict(key[1]), kind, self.tiers)
                    self._series[key] = series
                    dropped = False
            else:
                dropped = False
            if not dropped:
                series.feed(t, v)
                self._samples += 1
        if dropped:
            _M_TS_DROPPED.inc()
            return False
        _M_TS_SAMPLES.inc()
        return True

    # -- queries --------------------------------------------------------------

    def _match(self, name: str, labels: Optional[Dict[str, object]],
               ) -> List[_Series]:
        if labels is None:
            return [s for (n, _), s in self._series.items() if n == name]
        s = self._series.get((name, _label_key(labels)))
        return [s] if s is not None else []

    def _pick_tier(self, seconds: float) -> int:
        for i, (w, n) in enumerate(self.tiers):
            if w * n >= seconds:
                return i
        return len(self.tiers) - 1

    def range(self, name: str, seconds: float = 60.0,
              labels: Optional[Dict[str, object]] = None, *,
              at: Optional[float] = None,
              tier: Optional[int] = None) -> List[Dict[str, float]]:
        """Trailing-window buckets, oldest first.

        Reads the finest tier whose full span covers ``seconds``.
        ``labels=None`` merges every label-set of the name per epoch:
        min of mins, max of maxes, sum/count summed (so ``mean`` is the
        cross-series mean) and ``last`` the **max** of the member lasts —
        the worst-case convention alert predicates want (e.g. queue
        saturation across ports).
        """
        now = self.clock() if at is None else at
        ti = self._pick_tier(seconds) if tier is None else int(tier)
        merged: Dict[int, List[float]] = {}
        with self._lock:
            for series in self._match(name, labels):
                for (e, mn, mx, total, count, last
                     ) in series.rings[ti].buckets(now, seconds):
                    b = merged.get(e)
                    if b is None:
                        merged[e] = [mn, mx, total, count, last]
                    else:
                        if mn < b[0]:
                            b[0] = mn
                        if mx > b[1]:
                            b[1] = mx
                        b[2] += total
                        b[3] += count
                        if last > b[4]:
                            b[4] = last
        width = self.tiers[ti][0]
        return [{"t": e * width, "width": width, "min": b[0], "max": b[1],
                 "mean": b[2] / b[3], "count": int(b[3]), "last": b[4]}
                for e, b in sorted(merged.items())]

    def latest(self, name: str,
               labels: Optional[Dict[str, object]] = None,
               ) -> Optional[Tuple[float, float]]:
        """Most recent ``(t, value)`` across matching series, or None."""
        best: Optional[Tuple[float, float]] = None
        with self._lock:
            for series in self._match(name, labels):
                if series.last_t is None:
                    continue
                if best is None or series.last_t > best[0]:
                    best = (series.last_t, series.last_value)
        return best

    def rate(self, name: str, seconds: float = 60.0,
             labels: Optional[Dict[str, object]] = None, *,
             at: Optional[float] = None) -> Optional[float]:
        """Per-second increase of a cumulative series over the window.

        Counter-reset tolerant: bucket ``last`` values run through the
        federation plane's ``_CounterState`` delta, so a process restart
        mid-window contributes the post-reset value instead of a huge
        negative step. None with fewer than two buckets of evidence.
        """
        buckets = self.range(name, seconds, labels, at=at)
        if len(buckets) < 2:
            return None
        state = _CounterState()
        state.feed(buckets[0]["last"])
        for b in buckets[1:]:
            state.feed(b["last"])
        span = buckets[-1]["t"] - buckets[0]["t"]
        if span <= 0:
            return None
        return (state.acc - buckets[0]["last"]) / span

    def ewma(self, name: str, seconds: float = 60.0,
             labels: Optional[Dict[str, object]] = None, *,
             alpha: float = 0.3,
             at: Optional[float] = None) -> Optional[float]:
        """Exponentially weighted mean of bucket means, oldest→newest."""
        buckets = self.range(name, seconds, labels, at=at)
        if not buckets:
            return None
        value = buckets[0]["mean"]
        for b in buckets[1:]:
            value = alpha * b["mean"] + (1.0 - alpha) * value
        return value

    def sustained(self, name: str, predicate: Callable[[float], bool],
                  for_seconds: float,
                  labels: Optional[Dict[str, object]] = None, *,
                  field: str = "mean",
                  at: Optional[float] = None) -> bool:
        """True when ``predicate(bucket[field])`` held across the whole
        trailing window — evidence must reach back to the window start
        (a series younger than ``for_seconds`` is never "sustained"),
        and every observed bucket must satisfy the predicate."""
        now = self.clock() if at is None else at
        buckets = self.range(name, for_seconds, labels, at=now)
        if not buckets:
            return False
        # the bucket covering the window start has t <= now - for_seconds;
        # if the oldest evidence is younger, the signal hasn't been bad
        # (or even observed) long enough
        if buckets[0]["t"] > now - for_seconds:
            return False
        return all(predicate(b[field]) for b in buckets)

    # -- accounting / introspection -------------------------------------------

    def _bytes_per_series(self) -> int:
        slots = sum(n for _, n in self.tiers)
        # array columns dominate; the +512 is slack for the per-series
        # object, dict key, and label dict
        return slots * _STATS_PER_BUCKET * 8 + 512

    def byte_budget(self) -> int:
        """Provable upper bound on ring memory: cap × per-series cost."""
        return self.max_series * self._bytes_per_series()

    def approx_bytes(self) -> int:
        with self._lock:
            n = len(self._series)
        return n * self._bytes_per_series()

    def names(self) -> List[str]:
        with self._lock:
            return sorted({n for n, _ in self._series})

    def series_keys(self) -> List[Tuple[str, Dict[str, str]]]:
        with self._lock:
            return [(n, dict(lk)) for n, lk in sorted(self._series)]

    def stats(self) -> Dict[str, object]:
        with self._lock:
            n, samples, dropped = (len(self._series), self._samples,
                                   self._dropped)
        return {"series": n, "max_series": self.max_series,
                "samples": samples, "dropped": dropped,
                "tiers": [[w, s] for w, s in self.tiers],
                "approx_bytes": n * self._bytes_per_series(),
                "byte_budget": self.byte_budget()}

    def snapshot(self, seconds: float = 120.0, *,
                 names: Optional[Iterable[str]] = None,
                 at: Optional[float] = None) -> Dict[str, object]:
        """JSON-safe dump served at ``/debug/timeseries`` and embedded in
        bench phase records. Points are compact rows
        ``[t, mean, min, max, last, count]``."""
        now = self.clock() if at is None else at
        wanted = set(names) if names is not None else None
        out: List[Dict[str, object]] = []
        for name, labels in self.series_keys():
            if wanted is not None and name not in wanted:
                continue
            points = [[round(b["t"], 3), b["mean"], b["min"], b["max"],
                       b["last"], b["count"]]
                      for b in self.range(name, seconds, labels, at=now)]
            out.append({"name": name, "labels": labels, "points": points})
        return {"seconds": seconds, "point_fields":
                ["t", "mean", "min", "max", "last", "count"],
                "stats": self.stats(), "series": out}

    def clear(self) -> None:
        with self._lock:
            self._series.clear()
            self._samples = 0
            self._dropped = 0


# -- sparkline rendering ------------------------------------------------------

_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def _sparkline(values: Sequence[Optional[float]]) -> str:
    present = [v for v in values if v is not None]
    if not present:
        return ""
    lo, hi = min(present), max(present)
    span = hi - lo
    chars = []
    for v in values:
        if v is None:
            chars.append(" ")
        elif span <= 0:
            chars.append(_SPARK_BLOCKS[0])
        else:
            idx = int((v - lo) / span * (len(_SPARK_BLOCKS) - 1) + 0.5)
            chars.append(_SPARK_BLOCKS[idx])
    return "".join(chars)


def render_sparklines(store: TimeSeriesStore, seconds: float = 120.0, *,
                      names: Optional[Iterable[str]] = None,
                      width: int = 60,
                      at: Optional[float] = None) -> str:
    """Terminal triage view: one ``name{labels} ▁▃▅▇ min/max/last`` line
    per series (gaps render as spaces; long windows chunk-mean to fit)."""
    now = store.clock() if at is None else at
    wanted = set(names) if names is not None else None
    lines = []
    for name, labels in store.series_keys():
        if wanted is not None and name not in wanted:
            continue
        buckets = store.range(name, seconds, labels, at=now)
        if not buckets:
            continue
        tier_w = buckets[0]["width"]
        e_hi = int(now // tier_w)
        e_lo = min(int((now - seconds) // tier_w),
                   int(buckets[0]["t"] / tier_w))
        by_epoch = {int(b["t"] / tier_w): b["mean"] for b in buckets}
        values: List[Optional[float]] = [
            by_epoch.get(e) for e in range(e_lo, e_hi + 1)]
        if len(values) > width:
            chunk = math.ceil(len(values) / width)
            packed: List[Optional[float]] = []
            for i in range(0, len(values), chunk):
                window = [v for v in values[i:i + chunk] if v is not None]
                packed.append(sum(window) / len(window) if window else None)
            values = packed
        label = name + ("{%s}" % ",".join(
            f"{k}={v}" for k, v in sorted(labels.items())) if labels else "")
        lo = min(b["min"] for b in buckets)
        hi = max(b["max"] for b in buckets)
        lines.append(f"{label:<48} {_sparkline(values)}  "
                     f"min={lo:.4g} max={hi:.4g} "
                     f"last={buckets[-1]['last']:.4g}")
    return "\n".join(lines) + ("\n" if lines else "")


# -- alert rules and engine ---------------------------------------------------

_OPS: Dict[str, Callable[[float, float], bool]] = {
    "gt": lambda v, t: v > t, ">": lambda v, t: v > t,
    "lt": lambda v, t: v < t, "<": lambda v, t: v < t,
    "ge": lambda v, t: v >= t, ">=": lambda v, t: v >= t,
    "le": lambda v, t: v <= t, "<=": lambda v, t: v <= t,
}
_OP_CANON = {">": "gt", "<": "lt", ">=": "ge", "<=": "le"}


class AlertRule:
    """A sustained-threshold predicate over one store series.

    ``field`` picks the bucket statistic the predicate reads (``"max"``
    for spiky signals like queue saturation, ``"mean"`` for levels).
    """

    def __init__(self, name: str, series: str, op: str = "gt",
                 threshold: float = 0.0, *,
                 for_seconds: float = 2.0,
                 keep_firing_seconds: Optional[float] = None,
                 labels: Optional[Dict[str, object]] = None,
                 field: str = "mean", description: str = ""):
        if op not in _OPS:
            raise ValueError(f"unknown alert op {op!r}")
        self.name = str(name)
        self.series = str(series)
        self.op = _OP_CANON.get(op, op)
        self.threshold = float(threshold)
        self.for_seconds = float(for_seconds)
        self.keep_firing_seconds = (self.for_seconds
                                    if keep_firing_seconds is None
                                    else float(keep_firing_seconds))
        self.labels = dict(labels) if labels else None
        self.field = str(field)
        self.description = description
        self._cmp = _OPS[op]

    def predicate(self, value: float) -> bool:
        return self._cmp(value, self.threshold)

    def describe(self) -> Dict[str, object]:
        return {"name": self.name, "series": self.series, "op": self.op,
                "threshold": self.threshold,
                "for_seconds": self.for_seconds,
                "keep_firing_seconds": self.keep_firing_seconds,
                "labels": self.labels, "field": self.field,
                "description": self.description}


_BUNDLE_SEQ_LOCK = threading.Lock()
_BUNDLE_SEQ = 0


def _write_alert_bundle(rule: AlertRule,
                        record: Dict[str, object]) -> Optional[str]:
    """Default ``on_fire`` hook: watchdog-style atomic diagnostic bundle
    (tmp file + ``os.replace`` under the watchdog diag dir) embedding the
    offending series' recent window."""
    global _BUNDLE_SEQ
    try:
        from .watchdog import _SITE_SANITIZE_RE, get_watchdog
        diag_dir = get_watchdog().diag_dir()
    except Exception:
        return None
    with _BUNDLE_SEQ_LOCK:
        _BUNDLE_SEQ += 1
        seq = _BUNDLE_SEQ
    name = _SITE_SANITIZE_RE.sub("_", rule.name)[:64] or "rule"
    path = os.path.join(diag_dir,
                        f"alert_{name}_{os.getpid()}_{seq}.json")
    bundle = {"kind": "alert", **record}
    try:
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(bundle, fh, indent=2, sort_keys=True, default=str)
        os.replace(tmp, path)
    except OSError:
        return None
    return path


class AlertEngine:
    """Evaluates :class:`AlertRule` predicates with hysteresis.

    Lifecycle per rule: not-firing → (predicate sustained for
    ``for_seconds``) → firing → (latest bucket good continuously for
    ``keep_firing_seconds``) → resolved. Both edges emit an event-log
    entry, a ``mmlspark_alert_transitions_total{rule,to}`` bump, and
    set/clear ``mmlspark_alerts_firing{rule}``; the firing edge also
    runs the ``on_fire`` hooks (default: :func:`_write_alert_bundle`).
    """

    def __init__(self, store: TimeSeriesStore, *,
                 clock: Optional[Callable[[], float]] = None,
                 on_fire: Optional[Sequence[Callable[
                     [AlertRule, Dict[str, object]], object]]] = None):
        self.store = store
        self.clock = clock if clock is not None else store.clock
        self.on_fire: List[Callable[[AlertRule, Dict[str, object]],
                                    object]] = (
            [_write_alert_bundle] if on_fire is None else list(on_fire))
        self._lock = _new_lock("observability.timeseries.AlertEngine")
        self._rules: Dict[str, AlertRule] = {}
        # rule -> {"firing", "since", "last_bad", "value"}
        self._state: Dict[str, Dict[str, object]] = {}

    def add_rule(self, rule: AlertRule) -> None:
        with self._lock:
            self._rules[rule.name] = rule
            self._state.pop(rule.name, None)
        _M_ALERTS_FIRING.set(0.0, rule=rule.name)

    def remove_rule(self, name: str) -> None:
        with self._lock:
            self._rules.pop(name, None)
            self._state.pop(name, None)
        _M_ALERTS_FIRING.remove(rule=name)

    def rules(self) -> List[AlertRule]:
        with self._lock:
            return [self._rules[n] for n in sorted(self._rules)]

    def clear(self) -> None:
        for rule in self.rules():
            self.remove_rule(rule.name)

    def firing(self) -> List[str]:
        with self._lock:
            return sorted(n for n, st in self._state.items()
                          if st.get("firing"))

    def state(self) -> Dict[str, object]:
        out = {}
        with self._lock:
            for name in sorted(self._rules):
                rule = self._rules[name]
                st = self._state.get(name, {})
                out[name] = {**rule.describe(),
                             "firing": bool(st.get("firing")),
                             "since": st.get("since"),
                             "value": st.get("value")}
        return out

    def evaluate(self, at: Optional[float] = None,
                 ) -> List[Dict[str, object]]:
        """Run every rule once; returns the transitions that happened."""
        now = self.clock() if at is None else at
        transitions: List[Dict[str, object]] = []
        for rule in self.rules():
            latest = self.store.latest(rule.series, rule.labels)
            with self._lock:
                st = self._state.setdefault(
                    rule.name, {"firing": False, "since": None,
                                "last_bad": None, "value": None})
                firing = bool(st["firing"])
            value = latest[1] if latest is not None else None
            if not firing:
                if self.store.sustained(rule.series, rule.predicate,
                                        rule.for_seconds, rule.labels,
                                        field=rule.field, at=now):
                    record = self._transition(rule, st, now, value,
                                              to="firing")
                    transitions.append(record)
                    for hook in self.on_fire:
                        try:
                            hook(rule, record)
                        except Exception:
                            pass
                continue
            # firing: refresh the bad-mark while the latest bucket still
            # trips the predicate; resolve only after keep_firing_seconds
            # of continuously good evidence (hysteresis — no flapping)
            recent = self.store.range(
                rule.series, max(rule.for_seconds, rule.keep_firing_seconds),
                rule.labels, at=now)
            bad_now = bool(recent) and rule.predicate(
                recent[-1][rule.field])
            with self._lock:
                if bad_now:
                    st["last_bad"] = now
                last_bad = st["last_bad"]
            if (not bad_now and last_bad is not None
                    and now - float(last_bad) >= rule.keep_firing_seconds):
                transitions.append(self._transition(rule, st, now, value,
                                                    to="resolved"))
        return transitions

    def _transition(self, rule: AlertRule, st: Dict[str, object],
                    now: float, value: Optional[float], *,
                    to: str) -> Dict[str, object]:
        firing = to == "firing"
        with self._lock:
            st["firing"] = firing
            st["since"] = now if firing else None
            st["last_bad"] = now if firing else None
            st["value"] = value
        _M_ALERTS_FIRING.set(1.0 if firing else 0.0, rule=rule.name)
        _M_ALERT_TRANSITIONS.inc(rule=rule.name, to=to)
        record: Dict[str, object] = {
            "rule": rule.name, "to": to, "at": now, "value": value,
            **rule.describe()}
        if firing:
            record["window"] = self.store.range(
                rule.series,
                max(2.0 * rule.for_seconds, 10.0), rule.labels, at=now)
        log_event("alert_" + to, rule=rule.name, series=rule.series,
                  value=value, threshold=rule.threshold)
        return record


def default_alert_rules(*, for_seconds: float = 2.0,
                        keep_firing_seconds: float = 3.0,
                        ) -> List[AlertRule]:
    """The stock rule set wired to signals the repo already exports."""
    kw = {"for_seconds": for_seconds,
          "keep_firing_seconds": keep_firing_seconds}
    return [
        AlertRule("burn-rate", "mmlspark_slo_error_budget_burn",
                  "gt", 1.0, field="mean",
                  description="error-budget burn above 1x sustained", **kw),
        AlertRule("queue-saturation", "mmlspark_queue_saturation",
                  "gt", 0.8, field="max",
                  description="admission queue above 80% of capacity", **kw),
        AlertRule("breaker-flap",
                  "mmlspark_breaker_transitions_total:rate",
                  "gt", 0.5, field="mean",
                  description="circuit breakers transitioning faster than "
                              "0.5/s", **kw),
        AlertRule("kv-quant-error", "mmlspark_kv_quant_error",
                  "gt", 0.25, field="max",
                  description="quantized-KV reconstruction error above "
                              "tolerance", **kw),
    ]


def parse_alert_rules(spec: Optional[str] = None) -> List[AlertRule]:
    """Parse the ``MMLSPARK_TPU_ALERT_RULES`` grammar: ``;``-separated
    ``name:series:op:threshold[:for=S][:keep=S][:field=F]`` clauses.
    Malformed clauses are skipped (a bad env var must not kill a server).
    """
    if spec is None:
        spec = os.environ.get(RULES_ENV, "")
    rules: List[AlertRule] = []
    for clause in spec.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        parts = clause.split(":")
        if len(parts) < 4:
            continue
        name, series, op = parts[0], parts[1], parts[2]
        extras: Dict[str, object] = {}
        try:
            threshold = float(parts[3])
            for part in parts[4:]:
                k, _, v = part.partition("=")
                if k == "for":
                    extras["for_seconds"] = float(v)
                elif k == "keep":
                    extras["keep_firing_seconds"] = float(v)
                elif k == "field":
                    extras["field"] = v
            rules.append(AlertRule(name, series, op, threshold, **extras))
        except ValueError:
            continue
    return rules


# -- registry sampler (worker side) -------------------------------------------

class RegistrySampler:
    """Scrapes the metrics registry into a store on a fixed interval.

    Counters → ``name:rate`` (per-second, reset-protected), gauges →
    sampled directly, histograms → ``name:p50`` / ``name:p99`` over each
    interval's *new* observations. Extra callables attach via
    :meth:`add_source`. ``tick()`` is the synchronous unit of work (tests
    drive it directly with a fake clock); ``start()`` runs it on a daemon
    thread unless the interval is ``<= 0``.
    """

    def __init__(self, store: TimeSeriesStore, *,
                 interval: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 engine: Optional[AlertEngine] = None):
        self.store = store
        self.interval = sample_interval() if interval is None else interval
        self.clock = clock
        self.engine = engine
        self._counters: Dict[Tuple[str, tuple], _CounterState] = {}
        self._hists: Dict[Tuple[str, tuple],
                          Tuple[Dict[float, float], float]] = {}
        self._last_t: Optional[float] = None
        self._sources: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                            Callable[[], object]] = {}
        self._lock = _new_lock("observability.timeseries.RegistrySampler")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def add_source(self, name: str, fn: Callable[[], object],
                   **labels: object) -> None:
        """Attach a gauge-style callable sampled once per tick."""
        with self._lock:
            self._sources[(name, _label_key(labels))] = fn

    def remove_source(self, name: str, **labels: object) -> None:
        with self._lock:
            self._sources.pop((name, _label_key(labels)), None)

    def tick(self, now: Optional[float] = None) -> None:
        """One scrape: registry + extra sources, then alert evaluation."""
        if now is None:
            now = self.clock()
        dt = (now - self._last_t) if self._last_t is not None else None
        self._last_t = now
        try:
            snap = _registry_snapshot()
        except Exception:
            snap = {}
        for mname, metric in snap.items():
            if mname.startswith("mmlspark_timeseries_"):
                continue  # the store's own telemetry would self-amplify
            mtype = metric.get("type")
            for row in metric.get("series", ()):
                labels = row.get("labels") or {}
                key = (mname, _label_key(labels))
                if mtype == "counter":
                    state = self._counters.setdefault(key, _CounterState())
                    before = state.acc
                    state.feed(float(row.get("value", 0.0)))
                    if dt is not None and dt > 0:
                        self.store.record(mname + ":rate",
                                          (state.acc - before) / dt,
                                          labels, t=now, kind="rate")
                elif mtype == "gauge":
                    self.store.record(mname, row.get("value", 0.0),
                                      labels, t=now, kind="gauge")
                elif mtype == "histogram":
                    self._sample_histogram(key, row, now)
        with self._lock:
            sources = list(self._sources.items())
        for (name, lkey), fn in sources:
            try:
                value = fn()
            except Exception:
                continue
            if value is not None:
                self.store.record(name, value, dict(lkey), t=now)
        if self.engine is not None:
            try:
                self.engine.evaluate(at=now)
            except Exception:
                pass

    def _sample_histogram(self, key: Tuple[str, tuple],
                          row: Dict[str, object], now: float) -> None:
        raw = row.get("buckets") or {}
        cums: Dict[float, float] = {}
        for k, v in raw.items():  # cumulative, keyed repr(upper) / "+Inf"
            upper = math.inf if k == "+Inf" else float(k)
            cums[upper] = float(v)
        count = float(row.get("count", 0.0))
        prev = self._hists.get(key)
        if prev is None or count < prev[1]:  # first scrape or reset
            base, base_count = {}, 0.0
        else:
            base, base_count = prev
        self._hists[key] = (cums, count)
        d_count = count - base_count
        if d_count <= 0:
            return  # no new observations this interval
        uppers = sorted(cums)
        deltas_cum = [cums[u] - base.get(u, 0.0) for u in uppers]
        counts = [deltas_cum[0]] + [deltas_cum[i] - deltas_cum[i - 1]
                                    for i in range(1, len(deltas_cum))]
        labels = row.get("labels") or {}
        mname = key[0]
        for q, suffix in ((0.5, ":p50"), (0.99, ":p99")):
            self.store.record(
                mname + suffix,
                _quantile_from_counts(uppers, counts, d_count, q),
                labels, t=now, kind="quantile")

    def start(self) -> None:
        if self.interval <= 0 or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="mmlspark-ts-sampler", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception:
                pass

    def stop(self) -> None:
        thread = self._thread
        if thread is None:
            return
        self._stop.set()
        thread.join(timeout=2.0)
        self._thread = None


# -- cluster sampler (driver side) --------------------------------------------

class ClusterSampler:
    """Driver-side store fed from federation heartbeats — no thread.

    ``DriverRegistry.heartbeat`` calls :meth:`observe` after ingesting a
    worker's digest/telemetry, so cluster series accrue exactly where
    ``/debug/cluster`` observes the fleet: per-worker ``queue_depth`` /
    ``in_flight`` / ``hbm_bytes_in_use`` from the health digest, merged
    ``cluster_goodput_rps`` and ``cluster_burn_rate`` from the
    aggregator scorecard's monotone totals. Series are keyed by worker
    id, so a restarted worker (same id, fresh process) continues its
    series — counter resets are absorbed by the aggregator's own
    reset-safe merge before we ever see the totals.
    """

    _DIGEST_SERIES = (("cluster_queue_depth", "queue_depth"),
                      ("cluster_in_flight", "in_flight"),
                      ("cluster_hbm_bytes_in_use", "hbm_bytes_in_use"))

    def __init__(self, store: Optional[TimeSeriesStore] = None, *,
                 clock: Callable[[], float] = time.monotonic,
                 error_budget: float = 0.001):
        self.store = store if store is not None else TimeSeriesStore(
            clock=clock)
        self.clock = clock
        self.error_budget = float(error_budget)
        self._total = _CounterState()
        self._errors = _CounterState()
        self._last_t: Optional[float] = None

    def observe(self, worker_id: str,
                digest: Optional[Dict[str, object]] = None,
                scorecard: Optional[Dict[str, object]] = None) -> None:
        now = self.clock()
        if isinstance(digest, dict):
            for series, field in self._DIGEST_SERIES:
                value = digest.get(field)
                if isinstance(value, (int, float)):
                    self.store.record(series, float(value),
                                      {"worker": str(worker_id)}, t=now)
        if isinstance(scorecard, dict):
            total = errors = 0.0
            for cls in scorecard.get("classes", ()):
                total += float(cls.get("total", 0))
                errors += float(cls.get("errors_total", 0))
            before_t, before_e = self._total.acc, self._errors.acc
            self._total.feed(total)
            self._errors.feed(errors)
            dt = (now - self._last_t) if self._last_t is not None else None
            self._last_t = now
            if dt is not None and dt > 0:
                d_total = self._total.acc - before_t
                d_errors = self._errors.acc - before_e
                goodput = max(0.0, d_total - d_errors) / dt
                self.store.record("cluster_goodput_rps", goodput, t=now,
                                  kind="rate")
                burn = ((d_errors / d_total) / self.error_budget
                        if d_total > 0 else 0.0)
                self.store.record("cluster_burn_rate", burn, t=now,
                                  kind="rate")

    def snapshot(self, seconds: float = 300.0) -> Dict[str, object]:
        return self.store.snapshot(seconds)


# -- process-global wiring ----------------------------------------------------

_GLOBAL_LOCK = threading.RLock()
_STORE: Optional[TimeSeriesStore] = None
_ENGINE: Optional[AlertEngine] = None
_SAMPLER: Optional[RegistrySampler] = None
_SAMPLER_REFS = 0


def get_store() -> TimeSeriesStore:
    """The process-global store (worker side); created on first use."""
    global _STORE
    with _GLOBAL_LOCK:
        if _STORE is None:
            _STORE = TimeSeriesStore()
            _M_TS_SERIES.set_function(
                lambda: float(len(_STORE._series)) if _STORE else 0.0)
        return _STORE


def set_store(store: Optional[TimeSeriesStore],
              ) -> Optional[TimeSeriesStore]:
    global _STORE
    with _GLOBAL_LOCK:
        old, _STORE = _STORE, store
    return old


def reset_store() -> None:
    set_store(None)


def get_alert_engine() -> AlertEngine:
    """The global engine over :func:`get_store`, loaded with the default
    rules plus any ``MMLSPARK_TPU_ALERT_RULES`` overrides (same-name env
    rules replace the stock ones)."""
    global _ENGINE
    with _GLOBAL_LOCK:
        if _ENGINE is None:
            engine = AlertEngine(get_store())
            for rule in default_alert_rules():
                engine.add_rule(rule)
            for rule in parse_alert_rules():
                engine.add_rule(rule)
            _ENGINE = engine
        return _ENGINE


def set_alert_engine(engine: Optional[AlertEngine],
                     ) -> Optional[AlertEngine]:
    global _ENGINE
    with _GLOBAL_LOCK:
        old, _ENGINE = _ENGINE, engine
    return old


def reset_alert_engine() -> None:
    old = set_alert_engine(None)
    if old is not None:
        old.clear()


def acquire_sampler() -> RegistrySampler:
    """Refcounted acquisition of the global registry sampler.

    Every WorkerServer acquires on construction and releases on close;
    the scrape thread starts with the first holder and stops with the
    last (many in-process servers share one registry, so one sampler).
    """
    global _SAMPLER, _SAMPLER_REFS
    with _GLOBAL_LOCK:
        if _SAMPLER is None:
            _SAMPLER = RegistrySampler(get_store(),
                                       engine=get_alert_engine())
        _SAMPLER_REFS += 1
        sampler = _SAMPLER
    sampler.start()
    return sampler


def release_sampler() -> None:
    global _SAMPLER, _SAMPLER_REFS
    with _GLOBAL_LOCK:
        if _SAMPLER is None:
            return
        _SAMPLER_REFS = max(0, _SAMPLER_REFS - 1)
        sampler = _SAMPLER if _SAMPLER_REFS == 0 else None
        if sampler is not None:
            _SAMPLER = None
    if sampler is not None:
        sampler.stop()


def get_sampler() -> Optional[RegistrySampler]:
    return _SAMPLER
