"""Cluster-wide metrics federation over the heartbeat plane.

Every observability surface so far is per-process: each worker's
registry, SLO tracker, and cost ledger know only their own traffic. The
driver registry (``serving/distributed.py``) already hears from every
worker a few times a second — this module is the aggregation half that
turns those heartbeats into one cluster view:

- workers build a **compact telemetry snapshot** (:func:`worker_snapshot`
  — counters + histograms from the global registry plus the SLO class
  totals; gauges are deliberately excluded, summing a p99 gauge across
  workers is a lie) and piggyback it on the heartbeat at an env-gated
  interval (``MMLSPARK_TPU_FEDERATION_INTERVAL``), size-bounded by
  ``MMLSPARK_TPU_FEDERATION_MAX_BYTES``;
- the driver feeds them to a :class:`ClusterAggregator`, which merges
  per-series with **counter-reset detection**: per ``(worker, series)``
  it keeps the last reported value and an accumulated total, so a
  restarted worker (value drops below last) contributes its full new
  value instead of a negative delta — a merged counter **never goes
  backwards**;
- ``GET /debug/cluster`` on the driver serves the merged Prometheus
  text (:meth:`ClusterAggregator.render`), the cluster SLO scorecard
  (:meth:`ClusterAggregator.scorecard`), and the per-worker health
  digests the heartbeat carries.

The aggregator also maintains driver-local ``mmlspark_cluster_*``
metrics (worker count, snapshots ingested, resets detected) so the
federation plane is itself observable.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from .exposition import _escape_help, _escape_label, _fmt_value
from .registry import counter as _metric_counter
from .registry import gauge as _metric_gauge
from .registry import snapshot as _registry_snapshot
from .slo import get_tracker

__all__ = ["FEDERATION_INTERVAL_ENV", "FEDERATION_MAX_BYTES_ENV",
           "ClusterAggregator", "worker_snapshot", "snapshot_interval"]

#: seconds between telemetry snapshots attached to heartbeats; 0 attaches
#: on every heartbeat, negative disables federation entirely
FEDERATION_INTERVAL_ENV = "MMLSPARK_TPU_FEDERATION_INTERVAL"
#: upper bound on the serialized telemetry payload; oversized snapshots
#: shed histograms first, then metrics, keeping the SLO totals
FEDERATION_MAX_BYTES_ENV = "MMLSPARK_TPU_FEDERATION_MAX_BYTES"
DEFAULT_MAX_BYTES = 262144

_M_SNAPSHOTS = _metric_counter(
    "mmlspark_cluster_snapshots_total",
    "Worker telemetry snapshots ingested by the cluster aggregator")
_M_RESETS = _metric_counter(
    "mmlspark_cluster_counter_resets_total",
    "Counter resets detected while merging worker telemetry (worker "
    "restarts); merged counters absorb these without going backwards")
_M_WORKERS = _metric_gauge(
    "mmlspark_cluster_workers",
    "Workers the cluster aggregator has heard telemetry from")


def snapshot_interval() -> float:
    """The env-gated federation interval: seconds between snapshots
    (0 = every heartbeat), negative = disabled."""
    try:
        return float(os.environ.get(FEDERATION_INTERVAL_ENV, "0") or 0)
    except ValueError:
        return 0.0


def _slo_totals() -> List[dict]:
    """The SLO tracker's cumulative per-class totals — the only part of
    the scorecard that federates exactly (window views don't sum across
    skewed clocks)."""
    card = get_tracker().scorecard()
    return [{"transport": c["transport"], "route": c["route"],
             "model": c["model"], "tenant": c.get("tenant", "default"),
             "total": c["total"], "errors_total": c["errors_total"],
             "shed_total": c["shed_total"]}
            for c in card.get("classes", [])]


def worker_snapshot(max_bytes: Optional[int] = None) -> dict:
    """The compact telemetry payload a worker piggybacks on a heartbeat.

    ``{"metrics": {...}, "slo": {"classes": [...]}}`` — counters and
    histograms only (monotone series merge honestly; gauges don't).
    When the serialized payload exceeds the bound, histograms are shed
    first, then all metrics; the SLO totals always fit."""
    if max_bytes is None:
        try:
            max_bytes = int(os.environ.get(FEDERATION_MAX_BYTES_ENV,
                                           DEFAULT_MAX_BYTES))
        except ValueError:
            max_bytes = DEFAULT_MAX_BYTES
    full = _registry_snapshot()
    metrics = {name: m for name, m in full.items()
               if m.get("type") in ("counter", "histogram")}
    payload = {"metrics": metrics, "slo": {"classes": _slo_totals()}}
    if len(json.dumps(payload)) <= max_bytes:
        return payload
    payload["metrics"] = {name: m for name, m in metrics.items()
                          if m.get("type") == "counter"}
    if len(json.dumps(payload)) <= max_bytes:
        return payload
    return {"metrics": {}, "slo": {"slo_classes_only": True,
                                   "classes": _slo_totals()}}


def _series_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _CounterState:
    __slots__ = ("last", "acc")

    def __init__(self):
        self.last = 0.0
        self.acc = 0.0

    def feed(self, value: float) -> bool:
        """Accumulate a new cumulative reading; True on detected reset."""
        reset = value < self.last
        self.acc += value if reset else value - self.last
        self.last = value
        return reset


class _HistState:
    __slots__ = ("last_sum", "last_count", "last_buckets",
                 "acc_sum", "acc_count", "acc_buckets")

    def __init__(self):
        self.last_sum = self.last_count = 0.0
        self.last_buckets: Dict[str, float] = {}
        self.acc_sum = self.acc_count = 0.0
        self.acc_buckets: Dict[str, float] = {}

    def feed(self, s: dict) -> bool:
        count = float(s.get("count", 0.0))
        total = float(s.get("sum", 0.0))
        buckets = {str(k): float(v)
                   for k, v in (s.get("buckets") or {}).items()}
        # the count is the reset sentinel: a restarted worker's histogram
        # starts from zero in every field at once
        reset = count < self.last_count
        if reset:
            self.last_sum = self.last_count = 0.0
            self.last_buckets = {}
        self.acc_sum += total - self.last_sum
        self.acc_count += count - self.last_count
        for k, v in buckets.items():
            self.acc_buckets[k] = (self.acc_buckets.get(k, 0.0)
                                   + v - self.last_buckets.get(k, 0.0))
        self.last_sum, self.last_count = total, count
        self.last_buckets = buckets
        return reset


class _SloState:
    __slots__ = ("last", "acc")

    def __init__(self):
        self.last = {"total": 0.0, "errors_total": 0.0, "shed_total": 0.0}
        self.acc = {"total": 0.0, "errors_total": 0.0, "shed_total": 0.0}

    def feed(self, row: dict) -> bool:
        reset = float(row.get("total", 0.0)) < self.last["total"]
        if reset:
            self.last = {k: 0.0 for k in self.last}
        for k in self.acc:
            v = float(row.get(k, 0.0))
            self.acc[k] += v - self.last[k]
            self.last[k] = v
        return reset


class ClusterAggregator:
    """Merges per-worker telemetry into one monotone cluster view.

    Per ``(worker, series)`` state survives worker restarts and
    deregistrations on purpose: the merged counter is the sum of each
    worker's *accumulated* total, so a worker leaving (or resetting)
    never subtracts history from the cluster."""

    def __init__(self):
        self._lock = threading.Lock()
        # worker -> series-key -> state
        self._counters: Dict[str, Dict[tuple, _CounterState]] = {}
        self._hists: Dict[str, Dict[tuple, _HistState]] = {}
        self._slo: Dict[str, Dict[tuple, _SloState]] = {}
        # metric metadata (help/type/bucket keys) from the last snapshot
        # that carried each name
        self._meta: Dict[str, Dict[str, str]] = {}
        self._last_seen: Dict[str, float] = {}
        self.snapshots = 0
        self.resets = 0

    # -- ingest --------------------------------------------------------------
    def ingest(self, worker_id: str, telemetry: dict) -> None:
        """Feed one worker snapshot (:func:`worker_snapshot` shape).

        Malformed sub-structures are skipped series-by-series — one bad
        worker must not poison the cluster view."""
        if not isinstance(telemetry, dict):
            return
        worker_id = str(worker_id)
        resets = 0
        with self._lock:
            self.snapshots += 1
            self._last_seen[worker_id] = time.time()
            metrics = telemetry.get("metrics")
            if isinstance(metrics, dict):
                resets += self._ingest_metrics(worker_id, metrics)
            slo = telemetry.get("slo")
            if isinstance(slo, dict):
                resets += self._ingest_slo(worker_id, slo)
            self.resets += resets
            n_workers = len(self._last_seen)
        _M_SNAPSHOTS.inc()
        if resets:
            _M_RESETS.inc(resets)
        _M_WORKERS.set(n_workers)

    def _ingest_metrics(self, worker_id: str, metrics: dict) -> int:
        counters = self._counters.setdefault(worker_id, {})
        hists = self._hists.setdefault(worker_id, {})
        resets = 0
        for name, m in metrics.items():
            if not isinstance(m, dict):
                continue
            kind = m.get("type")
            if kind not in ("counter", "histogram"):
                continue
            self._meta[str(name)] = {"type": kind,
                                     "help": str(m.get("help", ""))}
            for s in m.get("series") or []:
                if not isinstance(s, dict):
                    continue
                labels = s.get("labels")
                if not isinstance(labels, dict):
                    continue
                key = (str(name), _series_key(labels))
                try:
                    if kind == "counter":
                        st = counters.get(key)
                        if st is None:
                            st = counters[key] = _CounterState()
                        resets += st.feed(float(s.get("value", 0.0)))
                    else:
                        st = hists.get(key)
                        if st is None:
                            st = hists[key] = _HistState()
                        resets += st.feed(s)
                except (TypeError, ValueError):
                    continue
        return resets

    def _ingest_slo(self, worker_id: str, slo: dict) -> int:
        states = self._slo.setdefault(worker_id, {})
        resets = 0
        for row in slo.get("classes") or []:
            if not isinstance(row, dict):
                continue
            key = (str(row.get("transport", "?")),
                   str(row.get("route", "?")),
                   str(row.get("model", "?")),
                   str(row.get("tenant", "default")))
            st = states.get(key)
            if st is None:
                st = states[key] = _SloState()
            try:
                resets += st.feed(row)
            except (TypeError, ValueError):
                continue
        return resets

    def forget(self, worker_id: str) -> None:
        """Stop counting ``worker_id`` toward the live-worker gauge. Its
        accumulated series stay in the merge — history is not deducted."""
        with self._lock:
            self._last_seen.pop(str(worker_id), None)
            n = len(self._last_seen)
        _M_WORKERS.set(n)

    # -- reading -------------------------------------------------------------
    def merged_snapshot(self) -> Dict[str, dict]:
        """Registry-``snapshot()``-shaped merge across all workers."""
        with self._lock:
            merged_c: Dict[str, Dict[tuple, float]] = {}
            for series in self._counters.values():
                for (name, labels), st in series.items():
                    merged_c.setdefault(name, {})
                    merged_c[name][labels] = (
                        merged_c[name].get(labels, 0.0) + st.acc)
            merged_h: Dict[str, Dict[tuple, list]] = {}
            for series in self._hists.values():
                for (name, labels), st in series.items():
                    acc = merged_h.setdefault(name, {}).get(labels)
                    if acc is None:
                        acc = merged_h[name][labels] = [0.0, 0.0, {}]
                    acc[0] += st.acc_sum
                    acc[1] += st.acc_count
                    for k, v in st.acc_buckets.items():
                        acc[2][k] = acc[2].get(k, 0.0) + v
            meta = dict(self._meta)
        out: Dict[str, dict] = {}
        for name in sorted(set(merged_c) | set(merged_h)):
            m = meta.get(name, {"type": "counter", "help": ""})
            series = []
            if name in merged_c:
                for labels, value in sorted(merged_c[name].items()):
                    series.append({"labels": dict(labels), "value": value})
            if name in merged_h:
                for labels, (total, count, buckets) in \
                        sorted(merged_h[name].items()):
                    series.append({"labels": dict(labels), "sum": total,
                                   "count": count,
                                   "buckets": dict(buckets)})
            out[name] = {"type": m["type"], "help": m["help"],
                         "series": series}
        return out

    def render(self) -> str:
        """Merged Prometheus text (exposition 0.0.4) — same line shapes
        as the per-worker ``/metrics``, values summed cluster-wide."""
        lines: List[str] = []
        for name, m in self.merged_snapshot().items():
            lines.append(f"# HELP {name} {_escape_help(m['help'])}")
            lines.append(f"# TYPE {name} {m['type']}")
            for s in m["series"]:
                labelstr = ",".join(
                    f'{k}="{_escape_label(str(v))}"'
                    for k, v in sorted(s["labels"].items()))
                if "buckets" in s:
                    for bk in sorted(s["buckets"],
                                     key=lambda k: (k == "+Inf",
                                                    _bucket_sort(k))):
                        le = f'le="{_le_value(bk)}"'
                        full = ",".join(x for x in (labelstr, le) if x)
                        lines.append(f"{name}_bucket{{{full}}} "
                                     f"{_fmt_value(s['buckets'][bk])}")
                    br = f"{{{labelstr}}}" if labelstr else ""
                    lines.append(f"{name}_sum{br} "
                                 f"{_fmt_value(s['sum'])}")
                    lines.append(f"{name}_count{br} "
                                 f"{_fmt_value(s['count'])}")
                else:
                    br = f"{{{labelstr}}}" if labelstr else ""
                    lines.append(f"{name}{br} {_fmt_value(s['value'])}")
        return "\n".join(lines) + "\n" if lines else ""

    def scorecard(self) -> Dict[str, object]:
        """Cluster SLO scorecard: cumulative per-class totals merged
        monotone across every worker ever heard from."""
        with self._lock:
            merged: Dict[tuple, Dict[str, float]] = {}
            for states in self._slo.values():
                for key, st in states.items():
                    acc = merged.setdefault(
                        key, {"total": 0.0, "errors_total": 0.0,
                              "shed_total": 0.0})
                    for k, v in st.acc.items():
                        acc[k] += v
            workers = len(self._last_seen)
            snapshots = self.snapshots
            resets = self.resets
        classes = []
        for (transport, route, model, tenant) in sorted(merged):
            acc = merged[(transport, route, model, tenant)]
            total = acc["total"]
            availability = ((total - acc["errors_total"]) / total
                            if total else None)
            classes.append({
                "transport": transport, "route": route, "model": model,
                "tenant": tenant, "total": int(acc["total"]),
                "errors_total": int(acc["errors_total"]),
                "shed_total": int(acc["shed_total"]),
                "availability": availability})
        return {"t": time.time(), "workers": workers,
                "snapshots": snapshots, "counter_resets": resets,
                "classes": classes}

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._hists.clear()
            self._slo.clear()
            self._meta.clear()
            self._last_seen.clear()
            self.snapshots = 0
            self.resets = 0
        _M_WORKERS.set(0)


def _bucket_sort(key: str) -> float:
    try:
        return float(key)
    except ValueError:
        return float("inf")


def _le_value(key: str) -> str:
    if key == "+Inf":
        return "+Inf"
    try:
        return _fmt_value(float(key))
    except ValueError:
        return key
