"""Device-stall watchdog: heartbeat watches + black-box diagnostic bundles.

BENCH_r05 died rc=124 inside a wedged device probe with zero runtime
diagnostics — the process had metrics and traces but nothing watching the
*long device calls themselves*. This module closes that hole:

- :func:`watch` is a context manager wrapped around every long device
  call (``BatchRunner.drain``'s ``device_get``, ``ContinuousDecoder``
  decode/prefill ticks, compile-cache warm-up, bench device probes). It
  registers a heartbeat; loops refresh it with ``.beat()``.
- a single :class:`Watchdog` daemon thread scans the active watches. A
  heartbeat stale past its budget fires **exactly once per stall**:
  ``mmlspark_watchdog_stalls_total{site}`` increments and an atomic
  black-box bundle (all-thread stacks via ``sys._current_frames`` +
  ``faulthandler``, the metrics ``snapshot()``, flight-recorder
  summaries, residency/KV-pool stats) lands under
  ``MMLSPARK_TPU_DIAG_DIR`` — so a post-mortem needs only the bundle,
  not a live process.

Disabled (the default — enable with ``MMLSPARK_TPU_WATCHDOG=1`` or
:func:`configure`), the hot path pays one attribute check: :func:`watch`
returns a shared no-op context, the same idiom as
``FaultInjector.enabled``. Knobs: ``MMLSPARK_TPU_WATCHDOG`` (enable),
``MMLSPARK_TPU_WATCHDOG_BUDGET`` (default per-watch budget, seconds),
``MMLSPARK_TPU_WATCHDOG_INTERVAL`` (scan period, seconds),
``MMLSPARK_TPU_DIAG_DIR`` (bundle directory).
"""

from __future__ import annotations

import faulthandler
import itertools
import json
import os
import re
import sys
import tempfile
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional

from .registry import counter as _metric_counter
from .registry import gauge as _metric_gauge
from .registry import snapshot as _registry_snapshot

__all__ = ["Watchdog", "watch", "get_watchdog", "set_watchdog",
           "reset_watchdog", "configure", "register_hbm_gauges",
           "register_bundle_provider", "unregister_bundle_provider",
           "DIAG_DIR_ENV", "WATCHDOG_ENV", "BUDGET_ENV", "INTERVAL_ENV"]

WATCHDOG_ENV = "MMLSPARK_TPU_WATCHDOG"
DIAG_DIR_ENV = "MMLSPARK_TPU_DIAG_DIR"
BUDGET_ENV = "MMLSPARK_TPU_WATCHDOG_BUDGET"
INTERVAL_ENV = "MMLSPARK_TPU_WATCHDOG_INTERVAL"

M_STALLS = _metric_counter(
    "mmlspark_watchdog_stalls_total",
    "Watched device calls whose heartbeat went stale past budget, by site",
    ("site",))
M_BUNDLES = _metric_counter(
    "mmlspark_watchdog_bundles_total",
    "Diagnostic bundles written (one per detected stall, best-effort)")
M_ACTIVE = _metric_gauge(
    "mmlspark_watchdog_active_watches",
    "Watches currently registered with the stall watchdog")

# per-device HBM occupancy, sampled at scrape time (registered by
# register_hbm_gauges when the backend supports memory_stats)
_M_HBM_IN_USE = _metric_gauge(
    "mmlspark_device_hbm_bytes_in_use",
    "Device memory in use (memory_stats; backends without it expose "
    "nothing)", ("device",))
_M_HBM_LIMIT = _metric_gauge(
    "mmlspark_device_hbm_bytes_limit",
    "Device memory limit (memory_stats)", ("device",))

_SITE_SANITIZE_RE = re.compile(r"[^A-Za-z0-9_.-]+")

# Extra stall-bundle blocks contributed by other subsystems (the journal
# registers one) without the watchdog importing them — the bundle must
# stay writable from a process where those layers never loaded.
_BUNDLE_PROVIDERS: Dict[str, Callable[[], object]] = {}


def register_bundle_provider(name: str, fn: Callable[[], object]) -> None:
    """Add a ``bundle[name] = fn()`` block to every future stall bundle.
    Provider failures degrade to an ``unavailable: ...`` string — a broken
    provider must never cost the stacks and metrics the bundle exists for."""
    _BUNDLE_PROVIDERS[name] = fn


def unregister_bundle_provider(name: str) -> None:
    _BUNDLE_PROVIDERS.pop(name, None)


def _truthy(value: Optional[str]) -> bool:
    return (value or "").strip().lower() in ("1", "true", "yes", "on")


class _NullWatch:
    """Shared no-op context for the disabled path — allocation-free."""

    __slots__ = ()

    def __enter__(self) -> "_NullWatch":
        return self

    def __exit__(self, *exc: object) -> None:
        pass

    def beat(self) -> None:
        pass


_NULL_WATCH = _NullWatch()


class _Watch:
    """One active heartbeat. ``beat()`` refreshes it (and re-arms the
    stall trigger, so a recovered-then-wedged loop fires again)."""

    __slots__ = ("site", "budget", "thread_ident", "thread_name",
                 "started", "last_beat", "stalled", "_wd", "_token")

    def __init__(self, wd: "Watchdog", site: str, budget: float):
        self.site = site
        self.budget = budget
        self._wd = wd
        self._token: Optional[int] = None
        self.thread_ident = 0
        self.thread_name = ""
        self.started = 0.0
        self.last_beat = 0.0
        self.stalled = False

    def __enter__(self) -> "_Watch":
        t = threading.current_thread()
        self.thread_ident = t.ident or 0
        self.thread_name = t.name
        self.started = self.last_beat = self._wd._clock()
        self.stalled = False
        self._token = self._wd._register(self)
        return self

    def __exit__(self, *exc: object) -> None:
        self._wd._unregister(self._token)

    def beat(self) -> None:
        self.last_beat = self._wd._clock()
        self.stalled = False


class Watchdog:
    """Daemon scanning the active watches for stale heartbeats.

    The scan thread starts lazily on the first registered watch and runs
    at ``interval`` seconds. ``clock`` is injectable for tests;
    :meth:`scan_once` runs one scan synchronously (no thread needed)."""

    def __init__(self, *, enabled: Optional[bool] = None,
                 interval: Optional[float] = None,
                 default_budget: Optional[float] = None,
                 diag_dir: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic):
        if enabled is None:
            enabled = _truthy(os.environ.get(WATCHDOG_ENV))
        if interval is None:
            interval = float(os.environ.get(INTERVAL_ENV, "0.5") or 0.5)
        if default_budget is None:
            default_budget = float(os.environ.get(BUDGET_ENV, "120") or 120)
        self.enabled = bool(enabled)
        self.interval = max(0.01, float(interval))
        self.default_budget = float(default_budget)
        self._diag_dir = diag_dir
        self._clock = clock
        self._lock = threading.Lock()
        self._watches: Dict[int, _Watch] = {}
        self._tokens = itertools.count()
        self._bundle_seq = itertools.count()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._callbacks: List[Callable[[dict], None]] = []
        #: (wall time, monotonic time) of the most recent stall, if any
        self.last_stall: Optional[Dict[str, float]] = None

    # -- watch registration --------------------------------------------------
    def watch(self, site: str, budget_seconds: Optional[float] = None):
        """Context manager guarding one long device call at ``site``.
        Falls back to the process default budget when none is given."""
        if not self.enabled:
            return _NULL_WATCH
        budget = (self.default_budget if budget_seconds is None
                  else float(budget_seconds))
        return _Watch(self, site, budget)

    def _register(self, w: _Watch) -> int:
        with self._lock:
            token = next(self._tokens)
            self._watches[token] = w
            M_ACTIVE.set(len(self._watches))
            if self._thread is None and self.enabled:
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._run, name="mmlspark-watchdog", daemon=True)
                self._thread.start()
        return token

    def _unregister(self, token: Optional[int]) -> None:
        if token is None:
            return
        with self._lock:
            self._watches.pop(token, None)
            M_ACTIVE.set(len(self._watches))

    def on_stall(self, cb: Callable[[dict], None]) -> None:
        """Register a callback invoked (from the scan thread) with each
        stall record — bench.py stamps its partial JSON through this."""
        with self._lock:
            self._callbacks.append(cb)

    # -- scanning ------------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.scan_once()
            except Exception:
                # the watchdog must never take the process down; a failed
                # scan retries on the next tick
                pass

    def scan_once(self) -> List[dict]:
        """One synchronous scan; returns the stall records fired (also the
        test hook — no daemon timing involved)."""
        now = self._clock()
        with self._lock:
            stale = [w for w in self._watches.values()
                     if not w.stalled and now - w.last_beat > w.budget]
            for w in stale:
                w.stalled = True
            callbacks = list(self._callbacks)
        records = []
        for w in stale:
            record = self._fire(w, now - w.last_beat)
            records.append(record)
            for cb in callbacks:
                try:
                    cb(record)
                except Exception:
                    pass
        return records

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2 * self.interval + 1.0)
            self._thread = None

    def last_stall_age(self) -> Optional[float]:
        """Seconds since the most recent stall, or None — the /healthz
        degraded check."""
        last = self.last_stall
        if last is None:
            return None
        return max(0.0, self._clock() - last["monotonic"])

    # -- stall handling ------------------------------------------------------
    def _fire(self, w: _Watch, stalled_for: float) -> dict:
        M_STALLS.inc(site=w.site)
        self.last_stall = {"wall": time.time(), "monotonic": self._clock(),
                           "site": w.site}
        record = {"site": w.site, "budget_seconds": w.budget,
                  "stalled_seconds": round(stalled_for, 3),
                  "thread": {"ident": w.thread_ident,
                             "name": w.thread_name},
                  "t": time.time(), "pid": os.getpid()}
        try:
            record["bundle"] = self._write_bundle(record)
        except Exception as e:
            record["bundle"] = None
            record["bundle_error"] = f"{type(e).__name__}: {e}"[:200]
        from .events import log_event
        log_event("watchdog_stall", site=w.site,
                  stalled_seconds=record["stalled_seconds"],
                  bundle=record.get("bundle"))
        return record

    def diag_dir(self) -> str:
        d = (self._diag_dir or os.environ.get(DIAG_DIR_ENV)
             or os.path.join(tempfile.gettempdir(), "mmlspark_tpu_diag"))
        os.makedirs(d, exist_ok=True)
        return d

    def _write_bundle(self, record: dict) -> str:
        """One atomic JSON bundle: tmp + ``os.replace`` so a reader never
        sees a torn file, and a killed writer leaves only ``*.tmp``."""
        bundle = dict(record)
        bundle["stacks"] = _thread_stacks()
        bundle["faulthandler"] = _faulthandler_dump()
        try:
            bundle["metrics"] = _registry_snapshot()
        except Exception as e:
            bundle["metrics"] = f"unavailable: {type(e).__name__}: {e}"
        try:
            from .tracing import get_flight_recorder
            bundle["traces"] = get_flight_recorder().summaries()
        except Exception as e:
            bundle["traces"] = f"unavailable: {type(e).__name__}: {e}"
        try:
            # guarded: residency imports jax; a jax-free process still
            # gets stacks + metrics
            from ..core.residency import residency_stats
            bundle["residency"] = residency_stats()
        except Exception:
            bundle["residency"] = None
        try:
            # which thread holds which sanitized lock, and for how long —
            # a stalled device call plus this table is usually the whole
            # deadlock/convoy diagnosis (empty when the sanitizer is off)
            from ..reliability.lock_sanitizer import held_by_thread
            bundle["locks_held"] = held_by_thread()
        except Exception:
            bundle["locks_held"] = None
        for name, fn in list(_BUNDLE_PROVIDERS.items()):
            try:
                bundle[name] = fn()
            except Exception as e:
                bundle[name] = f"unavailable: {type(e).__name__}: {e}"
        site = _SITE_SANITIZE_RE.sub("_", record["site"])[:64] or "site"
        name = (f"watchdog_{site}_{os.getpid()}_"
                f"{next(self._bundle_seq)}.json")
        path = os.path.join(self.diag_dir(), name)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(bundle, fh, default=str)
        os.replace(tmp, path)
        M_BUNDLES.inc()
        return path


def _thread_stacks() -> Dict[str, List[str]]:
    """``{"<ident> <name>": [formatted frames]}`` for every live thread —
    the stalled thread's stack is the bundle's reason for existing."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for ident, frame in sys._current_frames().items():
        key = f"{ident} {names.get(ident, '?')}"
        out[key] = traceback.format_stack(frame)
    return out


def _faulthandler_dump() -> str:
    """All-thread dump through faulthandler (C-level view: shows threads
    wedged inside native XLA calls that format_stack renders thin)."""
    try:
        with tempfile.TemporaryFile(mode="w+") as fh:
            faulthandler.dump_traceback(file=fh, all_threads=True)
            fh.seek(0)
            return fh.read()
    except Exception as e:
        return f"unavailable: {type(e).__name__}: {e}"


# -- the process-global watchdog ---------------------------------------------

_wd_lock = threading.Lock()
_WATCHDOG: Optional[Watchdog] = None


def get_watchdog() -> Watchdog:
    """The process-global watchdog (created on first use, enabled state
    from ``MMLSPARK_TPU_WATCHDOG``)."""
    global _WATCHDOG
    with _wd_lock:
        if _WATCHDOG is None:
            _WATCHDOG = Watchdog()
        return _WATCHDOG


def set_watchdog(wd: Optional[Watchdog]) -> None:
    global _WATCHDOG
    with _wd_lock:
        old = _WATCHDOG
        _WATCHDOG = wd
    if old is not None and old is not wd:
        old.stop()


def reset_watchdog() -> None:
    """Test hook: stop and drop the global watchdog so the next use
    re-reads the environment."""
    set_watchdog(None)


def configure(**kwargs) -> Watchdog:
    """Install a freshly-configured global watchdog (bench.py enables it
    programmatically: ``configure(enabled=True, default_budget=...)``)."""
    wd = Watchdog(**kwargs)
    set_watchdog(wd)
    return wd


def watch(site: str, budget_seconds: Optional[float] = None):
    """Module-level hot-path entry: ``with watch("runner_drain"): ...``.

    With the watchdog disabled this is one global read + one attribute
    check returning a shared no-op context — cheap enough for every
    drain/tick in the process (the ``injector.enabled`` idiom). The
    first call constructs the global (reading ``MMLSPARK_TPU_WATCHDOG``),
    so the env knob works without any route or configure() call having
    touched the watchdog first."""
    wd = _WATCHDOG
    if wd is None:
        wd = get_watchdog()
    if not wd.enabled:
        return _NULL_WATCH
    return wd.watch(site, budget_seconds)


def register_hbm_gauges() -> int:
    """Callback gauges for per-device HBM occupancy via ``memory_stats()``.

    Registers ``mmlspark_device_hbm_bytes_in_use{device}`` (sampled at
    scrape time) and stamps ``..._bytes_limit`` for every device whose
    backend reports memory stats; returns how many devices registered.
    Never *triggers* jax import or backend init (the build_info rule):
    a jax-free or uninitialized process registers nothing, quietly.
    """
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return 0
    try:
        from jax._src import xla_bridge as _xb
        if not _xb.backends_are_initialized():
            return 0
        devices = jax_mod.devices()
    except Exception:
        return 0
    n = 0
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats or "bytes_in_use" not in stats:
            continue
        label = f"{d.platform}:{d.id}"
        _M_HBM_IN_USE.set_function(
            lambda d=d: float((d.memory_stats() or {})
                              .get("bytes_in_use", 0)), device=label)
        limit = stats.get("bytes_limit") or stats.get(
            "bytes_reservable_limit")
        if limit:
            _M_HBM_LIMIT.set(float(limit), device=label)
        n += 1
    return n
