"""Request-scoped tracing: contextvars span trees, W3C traceparent, and a
bounded in-memory flight recorder.

Aggregate metrics (registry.py) answer "how slow is the p99?"; this module
answers "why was *this* request slow?" — the Dapper-style question. One
request = one trace: the serving transport opens a root span (ingesting an
inbound ``traceparent`` header so external callers correlate), every layer
underneath attaches child spans and span events (cache hit/miss, recompile,
pad-bucket chosen), and the completed tree lands in the flight recorder,
browsable at ``GET /debug/traces``.

Design constraints, matching the rest of observability/:

- **pure stdlib** — importable before jax; no I/O on the hot path.
- **contextvars, not threading.local** — the serving plane hops threads
  constantly (transport → dispatcher → prefetch worker → partition pool);
  a context is captured once with :func:`propagate` and re-installed in the
  worker, so spans opened there land in the right trace. The same
  ContextVar carries the installed ``SpanTracer`` (utils/profiling.py).
- **cheap when idle** — with no active trace, ``start_span`` returns an
  inert context manager and ``add_event`` is a dict lookup + None check;
  traces are only ever minted explicitly (:func:`start_trace`).
- **bounded** — traces cap their span/event counts, and the flight
  recorder keeps a ring of the last N traces plus an always-keep set for
  requests over the slow threshold, so memory is finite by construction.

Exemplars: :func:`set_exemplars` installs :func:`current_trace_id` as the
registry's exemplar provider, so latency histogram observations made under
an active span carry the trace_id into the OpenMetrics exposition
(``# {trace_id="..."}``). Default OFF — the rendered /metrics text stays
byte-identical to plain Prometheus 0.0.4 unless explicitly enabled.
"""

from __future__ import annotations

import contextvars
import functools
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional, Tuple

from . import registry as _registry

__all__ = [
    "Span",
    "Trace",
    "FlightRecorder",
    "new_trace_id",
    "new_span_id",
    "new_request_id",
    "parse_traceparent",
    "format_traceparent",
    "start_trace",
    "start_span",
    "activate",
    "add_event",
    "propagate",
    "current_span",
    "current_trace_id",
    "current_request_id",
    "install_tracer",
    "uninstall_tracer",
    "installed_tracer",
    "set_exemplars",
    "exemplars_enabled",
    "get_flight_recorder",
    "configure_recorder",
]

#: Hard cap on spans (and events per span) recorded into one trace — a
#: runaway loop attaching spans must degrade to a truncated trace, never
#: to unbounded memory. Drops are counted on the trace.
MAX_SPANS_PER_TRACE = 512
MAX_EVENTS_PER_SPAN = 64

#: The active span (one per logical request flow) and the installed
#: SpanTracer. contextvars so that ``contextvars.copy_context()`` captures
#: both in one shot for propagate(), and nested activations unwind
#: correctly on the same thread.
_SPAN: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "mmlspark_active_span", default=None)
_TRACER: "contextvars.ContextVar[Optional[object]]" = contextvars.ContextVar(
    "mmlspark_span_tracer", default=None)


# -- id minting (THE place request/trace/span ids come from: tpulint TPU008
# -- flags ad-hoc uuid4().hex minting elsewhere) ------------------------------
def new_trace_id() -> str:
    """128-bit lowercase-hex trace id (W3C trace-context format)."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """64-bit lowercase-hex span id (W3C trace-context format)."""
    return os.urandom(8).hex()


def new_request_id() -> str:
    """Serving-plane request id — same 32-hex shape the routing table and
    journal always used, minted here so tracing and routing stay joined."""
    return os.urandom(16).hex()


# -- W3C traceparent ----------------------------------------------------------
def _is_hex(s: str) -> bool:
    return bool(s) and all(c in "0123456789abcdef" for c in s)


def parse_traceparent(header: Optional[str]) -> Optional[Tuple[str, str]]:
    """``(trace_id, parent_span_id)`` from a ``traceparent`` header, or
    None when absent/malformed (per spec: a bad header starts a new trace,
    it never errors the request)."""
    if not header:
        return None
    parts = header.strip().lower().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id, flags = parts[0], parts[1], parts[2], parts[3]
    if len(version) != 2 or not _is_hex(version) or version == "ff":
        return None
    if version == "00" and len(parts) != 4:
        return None
    if len(trace_id) != 32 or not _is_hex(trace_id) \
            or trace_id == "0" * 32:
        return None
    if len(span_id) != 16 or not _is_hex(span_id) or span_id == "0" * 16:
        return None
    if len(flags) != 2 or not _is_hex(flags):
        return None
    return trace_id, span_id


def format_traceparent(span: "Span") -> str:
    """``00-{trace_id}-{span_id}-01`` for outbound hops / response echo."""
    return f"00-{span.trace_id}-{span.span_id}-01"


# -- span / trace data model --------------------------------------------------
class Span:
    """One timed operation inside a trace. End is idempotent — the first
    ``end()`` wins (a timed-out request later answered must not re-close
    its root), and ending the root hands the trace to the flight
    recorder."""

    __slots__ = ("trace", "name", "span_id", "parent_id", "attrs", "events",
                 "start_ts", "thread", "_start", "_dur")

    def __init__(self, name: str, trace: "Trace",
                 parent_id: Optional[str] = None,
                 attrs: Optional[dict] = None):
        self.trace = trace
        self.name = name
        self.span_id = new_span_id()
        self.parent_id = parent_id
        self.attrs = dict(attrs) if attrs else {}
        self.events: List[dict] = []
        self.start_ts = time.time()
        self.thread = threading.current_thread().name
        self._start = time.perf_counter()
        self._dur: Optional[float] = None

    @property
    def trace_id(self) -> str:
        return self.trace.trace_id

    @property
    def duration(self) -> Optional[float]:
        return self._dur

    @property
    def ended(self) -> bool:
        return self._dur is not None

    def event(self, name: str, **fields: object) -> None:
        """Attach a timestamped point event (cache miss, pad bucket, ...)."""
        with self.trace._lock:
            if len(self.events) >= MAX_EVENTS_PER_SPAN:
                self.trace.dropped += 1
                return
            self.events.append({
                "name": name, "ts": time.time(),
                **({"fields": fields} if fields else {})})

    def end(self, **attrs: object) -> bool:
        """Close the span; False when it was already closed (exactly-once).
        Ending the root span records the whole trace."""
        with self.trace._lock:
            if self._dur is not None:
                return False
            self._dur = time.perf_counter() - self._start
            if attrs:
                self.attrs.update(attrs)
        if self is self.trace.root:
            get_flight_recorder().record(self.trace)
        return True

    def to_dict(self) -> dict:
        return {"name": self.name, "span_id": self.span_id,
                "parent_id": self.parent_id, "trace_id": self.trace_id,
                "start_ts": self.start_ts, "duration_s": self._dur,
                "thread": self.thread, "attrs": dict(self.attrs),
                "events": list(self.events)}


class Trace:
    """All spans of one request, keyed by a W3C trace id."""

    def __init__(self, trace_id: str,
                 remote_parent_id: Optional[str] = None):
        self.trace_id = trace_id
        #: span id of the caller's span when the trace was ingested from an
        #: inbound traceparent — the upstream half lives in *their* tracer
        self.remote_parent_id = remote_parent_id
        self.root: Optional[Span] = None
        self.dropped = 0
        self._lock = threading.Lock()
        self._spans: List[Span] = []

    def _add(self, span: Span) -> bool:
        with self._lock:
            if len(self._spans) >= MAX_SPANS_PER_TRACE:
                self.dropped += 1
                return False
            self._spans.append(span)
            return True

    @property
    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    @property
    def duration(self) -> Optional[float]:
        return self.root.duration if self.root is not None else None

    def summary(self) -> dict:
        root = self.root
        return {"trace_id": self.trace_id,
                "name": root.name if root else None,
                "request_id": root.attrs.get("request_id") if root else None,
                "start_ts": root.start_ts if root else None,
                "duration_s": self.duration,
                "spans": len(self.spans),
                "dropped": self.dropped}

    def to_dict(self) -> dict:
        """Span TREE (children nested under parents) + the summary."""
        spans = self.spans
        nodes = {s.span_id: dict(s.to_dict(), children=[]) for s in spans}
        top: List[dict] = []
        for s in spans:
            parent = nodes.get(s.parent_id or "")
            (parent["children"] if parent is not None else top).append(
                nodes[s.span_id])
        return dict(self.summary(), roots=top)

    def to_chrome(self) -> dict:
        """Chrome-trace JSON — same shape ``SpanTracer.export`` writes, so
        one tooling path (chrome://tracing / Perfetto) reads both."""
        spans = self.spans
        t0 = min((s.start_ts for s in spans), default=0.0)
        threads: Dict[str, int] = {}
        events = []
        for s in spans:
            tid = threads.setdefault(s.thread, len(threads))
            events.append({
                "name": s.name, "ph": "X", "pid": 0, "tid": tid,
                "ts": (s.start_ts - t0) * 1e6,
                "dur": (s.duration or 0.0) * 1e6,
                "args": {**s.attrs, "span_id": s.span_id,
                         "trace_id": self.trace_id}})
        return {"traceEvents": events, "displayTimeUnit": "ms"}


# -- context management -------------------------------------------------------
def start_trace(name: str, traceparent: Optional[str] = None,
                **attrs: object) -> Span:
    """Mint a root span (a new trace, or a continuation of the caller's
    trace when ``traceparent`` parses). NOT activated — pair with
    :func:`activate`, and close it explicitly with ``span.end()``."""
    parent = parse_traceparent(traceparent)
    if parent is not None:
        trace = Trace(parent[0], remote_parent_id=parent[1])
        root = Span(name, trace, parent_id=parent[1], attrs=attrs)
    else:
        trace = Trace(new_trace_id())
        root = Span(name, trace, attrs=attrs)
    trace.root = root
    trace._add(root)
    return root


class _Activation:
    """``with activate(span):`` — install without owning: the span is NOT
    ended on exit (roots end at reply time, on another thread)."""

    __slots__ = ("_span", "_token")

    def __init__(self, span: Optional[Span]):
        self._span = span
        self._token = None

    def __enter__(self) -> Optional[Span]:
        if self._span is not None:
            self._token = _SPAN.set(self._span)
        return self._span

    def __exit__(self, *exc: object) -> None:
        if self._token is not None:
            _SPAN.reset(self._token)


def activate(span: Optional[Span]) -> _Activation:
    """Make ``span`` the current span for the with-block (no-op on None)."""
    return _Activation(span)


class _SpanScope:
    """``with start_span(...):`` — child span owned by the block: activated
    on enter, ended (and deactivated) on exit."""

    __slots__ = ("_span", "_token")

    def __init__(self, span: Optional[Span]):
        self._span = span
        self._token = None

    def __enter__(self) -> Optional[Span]:
        if self._span is not None:
            self._token = _SPAN.set(self._span)
        return self._span

    def __exit__(self, *exc: object) -> None:
        if self._token is not None:
            _SPAN.reset(self._token)
        if self._span is not None:
            self._span.end()


def start_span(name: str, **attrs: object) -> _SpanScope:
    """Open a child of the current span for the with-block. Inert (yields
    None) when no trace is active — library code can call this
    unconditionally; cost outside a trace is one ContextVar read."""
    parent = _SPAN.get()
    if parent is None:
        return _SpanScope(None)
    child = Span(name, parent.trace, parent_id=parent.span_id, attrs=attrs)
    if not parent.trace._add(child):
        return _SpanScope(None)
    return _SpanScope(child)


def add_event(name: str, **fields: object) -> None:
    """Attach a point event to the current span; no-op outside a trace."""
    span = _SPAN.get()
    if span is not None:
        span.event(name, **fields)


def current_span() -> Optional[Span]:
    return _SPAN.get()


def current_trace_id() -> Optional[str]:
    span = _SPAN.get()
    return span.trace_id if span is not None else None


def current_request_id() -> Optional[str]:
    """The request id of the active trace (stamped on the root span by the
    serving transport), falling back to the active span's own attr."""
    span = _SPAN.get()
    if span is None:
        return None
    root = span.trace.root
    rid = root.attrs.get("request_id") if root is not None else None
    return rid if rid is not None else span.attrs.get("request_id")


def propagate(fn: Callable) -> Callable:
    """Capture the CURRENT context (active span + installed tracer + any
    other ContextVars) and re-install it around every call of ``fn``.

    The explicit bridge across thread hops: plain ``threading.Thread`` /
    pool workers start with an EMPTY context, so spans opened there would
    silently fall outside the trace. Wrap the worker's callable at
    submission time::

        prepare = propagate(self._prepare)      # dispatch thread, in-trace
        PrefetchIterator((prepare(sl) for sl in slices), depth=2)

    Unlike ``Context.run`` the captured context is re-entered by value
    (set/reset per call), so one wrapped fn is safe to call concurrently
    from many workers."""
    captured = contextvars.copy_context()

    @functools.wraps(fn)
    def wrapped(*args: object, **kwargs: object):
        tokens = [(var, var.set(value)) for var, value in captured.items()]
        try:
            return fn(*args, **kwargs)
        finally:
            for var, token in reversed(tokens):
                var.reset(token)

    return wrapped


# -- SpanTracer installation (utils/profiling.py) -----------------------------
def install_tracer(tracer: object) -> "contextvars.Token":
    """Install a ``SpanTracer``-shaped object (has a ``span(name, **args)``
    context manager) as the context's active tracer."""
    return _TRACER.set(tracer)


def uninstall_tracer(token: "contextvars.Token") -> None:
    try:
        _TRACER.reset(token)
    except ValueError:
        # token minted in another context (enter/exit crossed threads) —
        # clearing beats leaving a dead tracer installed forever
        _TRACER.set(None)


def installed_tracer() -> Optional[object]:
    return _TRACER.get()


# -- exemplars ----------------------------------------------------------------
def set_exemplars(enabled: bool) -> None:
    """Toggle OpenMetrics exemplars: when on, histogram observations made
    under an active span capture the trace_id, and the exposition appends
    ``# {trace_id="..."} value`` to their bucket lines. Default off —
    /metrics stays byte-identical to plain Prometheus 0.0.4 text."""
    _registry.set_exemplar_provider(current_trace_id if enabled else None)


def exemplars_enabled() -> bool:
    return _registry.exemplar_provider() is not None


# -- flight recorder ----------------------------------------------------------
class FlightRecorder:
    """Bounded store of completed request traces.

    Two tiers: a ring of the last ``capacity`` traces (anything), plus an
    always-keep set (capped at ``slow_keep``, oldest evicted) for traces
    whose root duration meets ``slow_threshold`` — so the one slow request
    from an hour ago is still there after the ring wrapped ten thousand
    fast ones."""

    def __init__(self, capacity: int = 64, slow_threshold: float = 1.0,
                 slow_keep: int = 32):
        self._lock = threading.Lock()
        self.configure(capacity=capacity, slow_threshold=slow_threshold,
                       slow_keep=slow_keep)

    def configure(self, capacity: Optional[int] = None,
                  slow_threshold: Optional[float] = None,
                  slow_keep: Optional[int] = None) -> "FlightRecorder":
        with self._lock:
            if capacity is not None:
                self._ring: "deque[Trace]" = deque(
                    getattr(self, "_ring", ()), maxlen=max(1, int(capacity)))
            if slow_threshold is not None:
                self._slow_threshold = float(slow_threshold)
            if slow_keep is not None:
                self._slow_keep = max(1, int(slow_keep))
                if not hasattr(self, "_slow"):
                    self._slow: "OrderedDict[str, Trace]" = OrderedDict()
        return self

    @property
    def slow_threshold(self) -> float:
        return self._slow_threshold

    def record(self, trace: Trace) -> None:
        dur = trace.duration
        with self._lock:
            if dur is not None and dur >= self._slow_threshold:
                self._slow[trace.trace_id] = trace
                self._slow.move_to_end(trace.trace_id)
                while len(self._slow) > self._slow_keep:
                    self._slow.popitem(last=False)
            else:
                self._ring.append(trace)

    def get(self, trace_id: str) -> Optional[Trace]:
        with self._lock:
            trace = self._slow.get(trace_id)
            if trace is not None:
                return trace
            for t in self._ring:
                if t.trace_id == trace_id:
                    return t
        return None

    def traces(self) -> List[Trace]:
        """Newest first; slow-kept traces listed ahead of the ring."""
        with self._lock:
            slow = list(self._slow.values())
            ring = [t for t in self._ring if t.trace_id not in self._slow]
        return list(reversed(slow)) + list(reversed(ring))

    def summaries(self) -> List[dict]:
        return [t.summary() for t in self.traces()]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._slow.clear()


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


_RECORDER = FlightRecorder(
    capacity=_env_int("MMLSPARK_TPU_TRACE_RING", 64),
    slow_threshold=_env_float("MMLSPARK_TPU_TRACE_SLOW_SECONDS", 1.0),
    slow_keep=_env_int("MMLSPARK_TPU_TRACE_SLOW_KEEP", 32))


def get_flight_recorder() -> FlightRecorder:
    return _RECORDER


def configure_recorder(capacity: Optional[int] = None,
                       slow_threshold: Optional[float] = None,
                       slow_keep: Optional[int] = None) -> FlightRecorder:
    """Adjust the process-global recorder's knobs (tests, ops tuning)."""
    return _RECORDER.configure(capacity=capacity,
                               slow_threshold=slow_threshold,
                               slow_keep=slow_keep)
