"""Structured JSON event log on top of stdlib logging.

One event = one JSON object on one log line, under the
``mmlspark_tpu.events`` logger. Components emit through `log_event`
instead of ad-hoc ``print``/silenced handlers — notably the serving
plane's HTTP access lines (serving/server.py routes its suppressed
``log_message`` here at DEBUG, so request errors stay diagnosable by
raising the logger level rather than editing code).

Every emit also increments ``mmlspark_events_total{level=...}`` — even
when the logger level filters the line out — so tests and /metrics can
see event traffic without configuring logging handlers.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Optional

from .registry import counter as _counter
from .tracing import (current_request_id as _current_request_id,
                      current_trace_id as _current_trace_id)

LOGGER_NAME = "mmlspark_tpu.events"

__all__ = ["LOGGER_NAME", "EventLog", "get_event_log", "log_event"]

_M_EVENTS = _counter(
    "mmlspark_events_total",
    "Structured events emitted through the JSON event log",
    ("level",))


class EventLog:
    """Emit structured events as single-line JSON log records."""

    def __init__(self, logger: Optional[logging.Logger] = None) -> None:
        self._logger = logger or logging.getLogger(LOGGER_NAME)

    def emit(self, event: str, level: int = logging.INFO,
             **fields: object) -> None:
        """Log ``{"event": ..., "ts": ..., **fields}`` at `level`.

        When a trace context is active, ``trace_id``/``request_id`` are
        stamped onto the record (explicit fields win), so event lines join
        against /debug/traces span trees and journal entries.

        Never raises — telemetry must not take down the component
        emitting it (e.g. an HTTP handler mid-response).
        """
        try:
            _M_EVENTS.inc(level=logging.getLevelName(level).lower())
            if not self._logger.isEnabledFor(level):
                return
            record = {"event": event, "ts": time.time()}
            trace_id = _current_trace_id()
            if trace_id is not None:
                record["trace_id"] = trace_id
                request_id = _current_request_id()
                if request_id is not None:
                    record["request_id"] = request_id
            record.update(fields)
            self._logger.log(level, "%s",
                             json.dumps(record, sort_keys=True, default=str))
        except Exception:
            pass


_EVENT_LOG = EventLog()


def get_event_log() -> EventLog:
    return _EVENT_LOG


def log_event(event: str, level: int = logging.INFO,
              **fields: object) -> None:
    _EVENT_LOG.emit(event, level, **fields)
