"""Unified telemetry: metrics registry, Prometheus exposition, event log.

Pure stdlib (importable before jax), process-global, default-on. See
docs/observability.md for the metric catalog and label conventions; the
serving plane scrapes the global registry at ``GET /metrics``.
"""

from .events import EventLog, LOGGER_NAME, get_event_log, log_event
from .exposition import CONTENT_TYPE, render_prometheus
from .registry import (DEFAULT_LATENCY_BUCKETS, Counter, Gauge, Histogram,
                       MetricsRegistry, counter, gauge, get_registry,
                       histogram, render, reset_all, snapshot)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "DEFAULT_LATENCY_BUCKETS",
    "counter",
    "gauge",
    "histogram",
    "get_registry",
    "snapshot",
    "render",
    "reset_all",
    "CONTENT_TYPE",
    "render_prometheus",
    "EventLog",
    "LOGGER_NAME",
    "get_event_log",
    "log_event",
]
