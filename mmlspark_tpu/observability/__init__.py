"""Unified telemetry: metrics registry, Prometheus exposition, event log,
and request-scoped tracing.

Pure stdlib (importable before jax), process-global, default-on. See
docs/observability.md for the metric catalog, label conventions, and the
tracing/flight-recorder guide; the serving plane scrapes the global
registry at ``GET /metrics`` and serves recorded traces at
``GET /debug/traces``.
"""

from .events import EventLog, LOGGER_NAME, get_event_log, log_event
from .exposition import CONTENT_TYPE, render_prometheus
from .federation import ClusterAggregator, snapshot_interval, worker_snapshot
from .ledger import (COST_WEIGHTS, RESOURCES, CostLedger, charge, get_ledger,
                     reset_ledger, resolve_context, set_ledger)
from .registry import (DEFAULT_LATENCY_BUCKETS, Counter, Gauge, Histogram,
                       MetricsRegistry, build_info, counter, gauge,
                       get_registry, histogram, process_uptime_seconds,
                       render, reset_all, snapshot)
from .slo import (SloPolicy, SloTracker, classify_route, get_tracker,
                  reset_tracker, set_tracker)
from .tracing import (FlightRecorder, Span, Trace, activate, add_event,
                      configure_recorder, current_request_id, current_span,
                      current_trace_id, exemplars_enabled, format_traceparent,
                      get_flight_recorder, new_request_id, new_span_id,
                      new_trace_id, parse_traceparent, propagate,
                      set_exemplars, start_span, start_trace)
from .watchdog import (Watchdog, configure as configure_watchdog,
                       get_watchdog, register_hbm_gauges, reset_watchdog,
                       set_watchdog, watch)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "DEFAULT_LATENCY_BUCKETS",
    "counter",
    "gauge",
    "histogram",
    "get_registry",
    "snapshot",
    "render",
    "reset_all",
    "build_info",
    "process_uptime_seconds",
    "CONTENT_TYPE",
    "render_prometheus",
    "EventLog",
    "LOGGER_NAME",
    "get_event_log",
    "log_event",
    "Span",
    "Trace",
    "FlightRecorder",
    "start_trace",
    "start_span",
    "activate",
    "add_event",
    "propagate",
    "current_span",
    "current_trace_id",
    "current_request_id",
    "new_trace_id",
    "new_span_id",
    "new_request_id",
    "parse_traceparent",
    "format_traceparent",
    "set_exemplars",
    "exemplars_enabled",
    "get_flight_recorder",
    "configure_recorder",
    "SloPolicy",
    "SloTracker",
    "classify_route",
    "get_tracker",
    "set_tracker",
    "reset_tracker",
    "CostLedger",
    "COST_WEIGHTS",
    "RESOURCES",
    "charge",
    "get_ledger",
    "set_ledger",
    "reset_ledger",
    "resolve_context",
    "ClusterAggregator",
    "worker_snapshot",
    "snapshot_interval",
    "Watchdog",
    "watch",
    "get_watchdog",
    "set_watchdog",
    "reset_watchdog",
    "configure_watchdog",
    "register_hbm_gauges",
]
