"""Per-request cost attribution: who consumed that device time?

Every telemetry layer so far answers "how much" (metrics), "what
happened" (tracing), and "is it good enough" (SLO); none answers "which
request/tenant PAID for it". ROADMAP item 2 (multi-tenant serving with
weighted-fair queuing) needs exactly that truth, and the tuning
``CostModel`` wants attributed per-class cost rows next to its
throughput facts. This module is the measurement half: a process-global
:class:`CostLedger` the data plane charges as requests flow through it —

- **queue_wait_seconds** — time spent parked in the worker queue
  (``WorkerServer.get_batch`` charges on dequeue);
- **device_seconds** — dispatch+d2h wall time from ``BatchRunner`` runs
  and ``ContinuousDecoder`` prefill/decode ticks, apportioned per
  row/token across the requests sharing the batch;
- **compile_seconds** — XLA compiles triggered under the request;
- **h2d_bytes** / **d2h_bytes** — transfer volume from the residency/
  staging plane;
- **kv_page_seconds** — ``PagedKVPool`` page-holds (pages × held
  seconds, charged at free time);
- **padding_waste_rows** — rows of bucket padding the request's batch
  carried (capacity burned without useful work).

Charges resolve their **workload class** ``{transport, route, model,
tenant}`` from the active trace context: ``WorkerServer._enqueue`` stamps
the class onto the root span's attrs, so any code running under that
trace (directly or via ``tracing.propagate``) charges the right class
with zero plumbing. Code running outside any trace charges the bounded
``untraced`` class — the ledger never drops a cost on the floor.

Design constraints mirror the SLO tracker's (slo.py): pure stdlib,
default-on (a dict lookup and a few float adds per charge), process
global (:func:`get_ledger`), resettable, cardinality-bounded by the same
``MAX_CLASSES`` overflow-to-"other" discipline, and snapshot-able
(:meth:`CostLedger.snapshot` returns plain JSON served at
``GET /debug/costs`` and harvested by
``tuning.observations.harvest_costs`` as ``source="cost_ledger"`` rows).

The **heavy-hitter table** is a SpaceSaving sketch over trace ids: the
top-K most expensive requests by weighted scalar cost, each entry
carrying the maximum overestimation error its slot inherited. Entries
join back to the flight recorder by trace id
(``GET /debug/traces/<trace_id>``), so "what did the most expensive
request actually do" is one click, not a log dig.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

from .registry import counter as _metric_counter
from .registry import gauge as _metric_gauge
from .slo import DEFAULT_TENANT, MAX_CLASSES, classify_route
from .tracing import current_span

__all__ = ["RESOURCES", "COST_WEIGHTS", "CostLedger", "get_ledger",
           "set_ledger", "reset_ledger", "charge", "resolve_context"]

#: every resource the ledger accounts; charges to other names raise
RESOURCES = ("queue_wait_seconds", "device_seconds", "compile_seconds",
             "h2d_bytes", "d2h_bytes", "kv_page_seconds",
             "padding_waste_rows")

#: scalarization weights for the heavy-hitter ranking — device time is
#: the unit (1.0); bytes and pages are scaled so a typical request's
#: transfer volume lands in the same order of magnitude as its compute
COST_WEIGHTS: Dict[str, float] = {
    "queue_wait_seconds": 0.1,       # waiting burns latency, not devices
    "device_seconds": 1.0,
    "compile_seconds": 1.0,
    "h2d_bytes": 1e-9,               # ~1 GB ≈ 1 device-second
    "d2h_bytes": 1e-9,
    "kv_page_seconds": 0.01,         # holding HBM is cheaper than using it
    "padding_waste_rows": 1e-4,
}

#: env knob: heavy-hitter table capacity (docs/performance.md)
TOPK_ENV = "MMLSPARK_TPU_COST_TOPK"
DEFAULT_TOP_K = 32

_M_COST = _metric_counter(
    "mmlspark_cost_total",
    "Attributed resource consumption by workload class; units are per "
    "resource (seconds, bytes, page-seconds, rows)",
    ("transport", "route", "model", "tenant", "resource"))
_M_COST_CHARGES = _metric_counter(
    "mmlspark_cost_charges_total",
    "Individual ledger charges by workload class",
    ("transport", "route", "model", "tenant"))
_M_COST_HH = _metric_gauge(
    "mmlspark_cost_heavy_hitters",
    "Entries currently held by the ledger's top-K heavy-hitter table")

_UNTRACED = ("untraced", "untraced", "default", DEFAULT_TENANT)
_OVERFLOW = ("other", "other", "other", "other")

ClassKey = Tuple[str, str, str, str]


def resolve_context() -> Tuple[ClassKey, Optional[str]]:
    """``(workload class, trace id)`` for the active trace context.

    The class comes from the root span's attrs (stamped by
    ``WorkerServer._enqueue``): ``transport``, ``route`` (falling back to
    :func:`classify_route` over the stamped ``url``), ``model``,
    ``tenant``. Outside any trace: the ``untraced`` class and no id."""
    span = current_span()
    if span is None:
        return _UNTRACED, None
    root = span.trace.root
    attrs = root.attrs if root is not None else span.attrs
    route = attrs.get("route")
    if route is None:
        route = classify_route(attrs.get("url"))
    key = (str(attrs.get("transport", "untraced")), str(route),
           str(attrs.get("model", "default")),
           str(attrs.get("tenant", DEFAULT_TENANT)))
    return key, span.trace.trace_id


class _HeavyHitters:
    """SpaceSaving top-K over trace ids, keyed by weighted scalar cost.

    A full table evicts its cheapest entry; the newcomer inherits the
    victim's cost as its overestimation floor (``error``), the classic
    Metwally et al. guarantee: true cost ∈ [cost - error, cost]."""

    __slots__ = ("k", "_items")

    def __init__(self, k: int):
        self.k = max(1, int(k))
        # trace_id -> [cost, error, class_key]
        self._items: Dict[str, list] = {}

    def add(self, trace_id: str, weighted: float, key: ClassKey) -> None:
        e = self._items.get(trace_id)
        if e is not None:
            e[0] += weighted
            e[2] = key
            return
        if len(self._items) < self.k:
            self._items[trace_id] = [weighted, 0.0, key]
            return
        victim = min(self._items, key=lambda t: self._items[t][0])
        floor = self._items.pop(victim)[0]
        self._items[trace_id] = [floor + weighted, floor, key]

    def top(self) -> List[dict]:
        rows = sorted(self._items.items(), key=lambda kv: -kv[1][0])
        return [{"trace_id": tid, "cost": round(cost, 9),
                 "error": round(err, 9),
                 "transport": key[0], "route": key[1], "model": key[2],
                 "tenant": key[3]}
                for tid, (cost, err, key) in rows]

    def __len__(self) -> int:
        return len(self._items)


class _ClassCosts:
    __slots__ = ("resources", "charges")

    def __init__(self):
        self.resources: Dict[str, float] = {r: 0.0 for r in RESOURCES}
        self.charges = 0


class CostLedger:
    """Process-global per-class resource accounting + top-K heavy hitters.

    All mutation is under one lock; the per-charge cost is a dict lookup
    plus a few float adds (the mirrored counter increments outside the
    lock, same ordering discipline as the SLO tracker's)."""

    def __init__(self, max_classes: int = MAX_CLASSES,
                 top_k: Optional[int] = None):
        if top_k is None:
            try:
                top_k = int(os.environ.get(TOPK_ENV, DEFAULT_TOP_K))
            except ValueError:
                top_k = DEFAULT_TOP_K
        self._max_classes = int(max_classes)
        self._lock = threading.Lock()
        self._classes: Dict[ClassKey, _ClassCosts] = {}
        self._hh = _HeavyHitters(top_k)

    # -- charging ------------------------------------------------------------
    def _class(self, key: ClassKey) -> _ClassCosts:
        cls = self._classes.get(key)
        if cls is None:
            if len(self._classes) >= self._max_classes:
                key = _OVERFLOW
                cls = self._classes.get(key)
                if cls is not None:
                    return cls
            cls = self._classes[key] = _ClassCosts()
        return cls

    def charge(self, resource: str, amount: float,
               cls: Optional[ClassKey] = None,
               trace_id: Optional[str] = None) -> None:
        """Charge ``amount`` of ``resource`` to a workload class.

        With no explicit ``cls``/``trace_id`` both resolve from the
        active trace context (:func:`resolve_context`) — the common path
        for code already running under the request's span. Explicit
        arguments serve deferred charges (KV page frees, decoder tick
        apportionment) where the consuming context is long gone."""
        if resource not in COST_WEIGHTS:
            raise ValueError(f"unknown ledger resource: {resource!r}")
        amount = float(amount)
        if amount <= 0.0:
            return
        if cls is None:
            cls, ambient_tid = resolve_context()
            if trace_id is None:
                trace_id = ambient_tid
        weighted = amount * COST_WEIGHTS[resource]
        with self._lock:
            c = self._class(cls)
            c.resources[resource] += amount
            c.charges += 1
            if trace_id:
                self._hh.add(trace_id, weighted, cls)
            hh_len = len(self._hh)
        _M_COST.inc(amount, transport=cls[0], route=cls[1], model=cls[2],
                    tenant=cls[3], resource=resource)
        _M_COST_CHARGES.inc(transport=cls[0], route=cls[1], model=cls[2],
                            tenant=cls[3])
        _M_COST_HH.set(hh_len)

    def charge_shares(self, resource: str, amount: float,
                      shares: Iterable[Tuple[ClassKey, Optional[str],
                                             float]]) -> None:
        """Apportion ``amount`` across ``(cls, trace_id, weight)`` shares.

        The decoder's per-tick device time is one measurement covering
        many live slots: each slot gets ``amount * weight / sum(weights)``
        charged to its own class and trace. Zero/negative weights drop
        out; an empty share list charges nothing."""
        shares = [(cls, tid, float(w)) for cls, tid, w in shares
                  if float(w) > 0.0]
        total = sum(w for _, _, w in shares)
        if total <= 0.0:
            return
        for cls, tid, w in shares:
            self.charge(resource, amount * (w / total), cls=cls,
                        trace_id=tid)

    # -- reading -------------------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        """JSON-safe ledger view: per-class resource totals + weighted
        scalar cost, the heavy-hitter table (descending cost), and the
        weights the scalarization used."""
        with self._lock:
            items = sorted(self._classes.items())
            views = [(key, dict(c.resources), c.charges)
                     for key, c in items]
            hh = self._hh.top()
            top_k = self._hh.k
        classes: List[Dict[str, object]] = []
        for (transport, route, model, tenant), res, charges in views:
            weighted = sum(res[r] * COST_WEIGHTS[r] for r in RESOURCES)
            classes.append({
                "transport": transport, "route": route, "model": model,
                "tenant": tenant, "charges": charges,
                "resources": {r: round(v, 9) for r, v in res.items()},
                "weighted_cost": round(weighted, 9)})
        return {"t": time.time(), "top_k": top_k,
                "weights": dict(COST_WEIGHTS),
                "classes": classes, "heavy_hitters": hh}

    def class_totals(self, resource: str) -> Dict[ClassKey, float]:
        """``{class: total}`` for one resource (test/reconciliation aid)."""
        with self._lock:
            return {key: c.resources.get(resource, 0.0)
                    for key, c in self._classes.items()}

    def reset(self) -> None:
        with self._lock:
            self._classes.clear()
            self._hh = _HeavyHitters(self._hh.k)


# -- the process-global ledger ------------------------------------------------

_ledger_lock = threading.Lock()
_ledger: Optional[CostLedger] = None


def get_ledger() -> CostLedger:
    """The process-global ledger, created on first use — the one every
    charge site (server, runner, decoder, pools, residency) reports to."""
    global _ledger
    with _ledger_lock:
        if _ledger is None:
            _ledger = CostLedger()
        return _ledger


def set_ledger(ledger: Optional[CostLedger]) -> None:
    """Install a specific ledger (tests, custom top-K)."""
    global _ledger
    with _ledger_lock:
        _ledger = ledger


def reset_ledger() -> None:
    """Drop the global ledger (test hook — pair with
    ``observability.reset_all`` to zero the mirrored metric series)."""
    set_ledger(None)


def charge(resource: str, amount: float,
           cls: Optional[ClassKey] = None,
           trace_id: Optional[str] = None) -> None:
    """Module-level convenience: ``get_ledger().charge(...)`` — the
    one-liner charge sites import."""
    get_ledger().charge(resource, amount, cls=cls, trace_id=trace_id)
