"""Evaluation plots: confusion matrix and ROC over DataFrame columns.

Parity surface: ``synapse.ml.plot`` (reference
``core/src/main/python/synapse/ml/plot/plot.py:17-62``) — ``confusionMatrix``
and ``roc`` helpers that render directly from prediction columns. Here the
statistics come from our own metrics (no sklearn dependency), matplotlib is
imported lazily, and each helper RETURNS the computed arrays so headless
callers (CI, notebooks exporting JSON) can use the numbers without a
display.
"""

from __future__ import annotations

import itertools
from typing import Optional, Sequence

import numpy as np

__all__ = ["confusion_matrix", "roc"]


def _columns(df, *names):
    return [np.asarray(df[n]) for n in names]


def confusion_matrix(df, y_col: str, y_hat_col: str,
                     labels: Optional[Sequence] = None, ax=None,
                     render: bool = True) -> np.ndarray:
    """Confusion matrix of ``y_hat_col`` vs ``y_col``; renders onto
    matplotlib (row-normalized heat map with counts and accuracy, the
    reference's layout) when ``render`` and returns the raw count matrix."""
    y, y_hat = _columns(df, y_col, y_hat_col)
    if labels is None:
        # numeric order for numbers, type-grouped otherwise — the repo's
        # label-ordering convention (train/metrics.py)
        labels = sorted(set(np.unique(y)) | set(np.unique(y_hat)),
                        key=lambda v: (str(type(v)), v))
    index = {v: i for i, v in enumerate(labels)}
    n = len(labels)
    from .train.metrics import confusion_matrix as _cm
    yt = np.asarray([index[v] for v in y], np.int64)
    yp = np.asarray([index[v] for v in y_hat], np.int64)
    cm = _cm(yt, yp, n)
    if not render:
        return cm
    import matplotlib.pyplot as plt
    ax = ax or plt.gca()
    accuracy = float(np.mean(y == y_hat))
    cmn = cm.astype(float) / np.maximum(cm.sum(axis=1)[:, None], 1)
    ax.text(-.3, -.55, f"$Accuracy$ $=$ ${round(accuracy * 100, 1)}\\%$",
            fontsize=18)
    ticks = np.arange(n)
    ax.set_xticks(ticks, [str(v) for v in labels])
    ax.set_yticks(ticks, [str(v) for v in labels])
    ax.imshow(cmn, interpolation="nearest", cmap="Blues", vmin=0, vmax=1)
    for i, j in itertools.product(range(n), range(n)):
        ax.text(j, i, str(cm[i, j]), horizontalalignment="center",
                fontsize=18, color="white" if cmn[i, j] > .1 else "black")
    ax.set_xlabel("Predicted Label", fontsize=18)
    ax.set_ylabel("True Label", fontsize=18)
    return cm


def roc(df, y_col: str, y_hat_col: str, thresh: float = .5, ax=None,
        render: bool = True):
    """ROC curve of score column ``y_hat_col`` against binarized
    ``y_col`` (> ``thresh``). Returns ``(fpr, tpr, thresholds)`` and plots
    the curve when ``render``."""
    y, scores = _columns(df, y_col, y_hat_col)
    y = (y > thresh).astype(np.int64)
    order = np.argsort(-scores, kind="stable")
    ys = y[order]
    ss = scores[order]
    tp = np.cumsum(ys)
    fp = np.cumsum(1 - ys)
    # one curve point per distinct score (the sklearn roc_curve convention)
    last = np.r_[np.nonzero(np.diff(ss))[0], len(ss) - 1]
    tpr = tp[last] / max(tp[-1], 1)
    fpr = fp[last] / max(fp[-1], 1)
    tpr = np.r_[0.0, tpr]
    fpr = np.r_[0.0, fpr]
    thresholds = np.r_[np.inf, ss[last]]
    if render:
        import matplotlib.pyplot as plt
        ax = ax or plt.gca()
        ax.plot(fpr, tpr)
        ax.set_xlabel("False Positive Rate", fontsize=20)
        ax.set_ylabel("True Positive Rate", fontsize=20)
    return fpr, tpr, thresholds
