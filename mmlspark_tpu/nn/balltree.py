"""Serializable ball tree with label-conditioned search.

Parity surface: ``BallTree``/``ConditionalBallTree`` (reference
``core/.../nn/BallTree.scala:31,158``) and ``BoundedPriorityQueue:21``.

The tree is stored as flat numpy arrays (centers, radii, children, point
ranges) so it round-trips through the ComplexParam pytree codec. Search is
host-side branch-and-bound — the device path for bulk queries is the
brute-force MXU matmul in ``knn.py``; the tree serves the
ConditionalKNN case (per-query label filters) the reference runs on the JVM.
"""

from __future__ import annotations

# tpulint: disable-file=TPU004 — deliberate host-side float64: the tree is
# exact branch-and-bound geometry on the host (reference-parity with the
# JVM BallTree); nothing here feeds a device, the bulk path in knn.py
# casts to float32 before device_put.

import heapq
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["BallTree"]


class BallTree:
    def __init__(self, points: np.ndarray, labels: Optional[Sequence] = None,
                 leaf_size: int = 50):
        self.points = np.asarray(points, dtype=np.float64)
        self.labels = None if labels is None else np.asarray(labels)
        self.leaf_size = int(leaf_size)
        n = len(self.points)
        self.index = np.arange(n)
        centers: List[np.ndarray] = []
        radii: List[float] = []
        lefts: List[int] = []
        rights: List[int] = []
        starts: List[int] = []
        ends: List[int] = []

        def build(lo: int, hi: int) -> int:
            node = len(centers)
            pts = self.points[self.index[lo:hi]]
            center = pts.mean(axis=0)
            d = np.linalg.norm(pts - center, axis=1)
            centers.append(center)
            radii.append(float(d.max()) if len(d) else 0.0)
            lefts.append(-1)
            rights.append(-1)
            starts.append(lo)
            ends.append(hi)
            if hi - lo > self.leaf_size:
                # split on the direction between two far points (cheap 2-means)
                far1 = self.index[lo + int(np.argmax(d))]
                d2 = np.linalg.norm(pts - self.points[far1], axis=1)
                far2 = self.index[lo + int(np.argmax(d2))]
                direction = self.points[far2] - self.points[far1]
                proj = pts @ direction
                order = np.argsort(proj, kind="stable")
                self.index[lo:hi] = self.index[lo:hi][order]
                mid = (lo + hi) // 2
                lefts[node] = build(lo, mid)
                rights[node] = build(mid, hi)
            return node

        if n:
            build(0, n)
        self.centers = np.asarray(centers)
        self.radii = np.asarray(radii)
        self.lefts = np.asarray(lefts, dtype=np.int64)
        self.rights = np.asarray(rights, dtype=np.int64)
        self.starts = np.asarray(starts, dtype=np.int64)
        self.ends = np.asarray(ends, dtype=np.int64)

    # -- persistence (pytree of arrays) -------------------------------------
    def to_tree(self) -> Dict[str, np.ndarray]:
        out = {k: getattr(self, k) for k in
               ("points", "index", "centers", "radii", "lefts", "rights",
                "starts", "ends")}
        out["leaf_size"] = np.asarray(self.leaf_size)
        if self.labels is not None:
            out["labels"] = self.labels
        return out

    @classmethod
    def from_tree(cls, tree: Dict[str, np.ndarray]) -> "BallTree":
        obj = cls.__new__(cls)
        for k in ("points", "index", "centers", "radii", "lefts", "rights",
                  "starts", "ends"):
            setattr(obj, k, np.asarray(tree[k]))
        obj.leaf_size = int(np.asarray(tree["leaf_size"]))
        obj.labels = np.asarray(tree["labels"]) if "labels" in tree else None
        return obj

    # -- search -------------------------------------------------------------
    def query(self, q: np.ndarray, k: int = 1,
              allowed_labels: Optional[set] = None):
        """k nearest neighbours of ``q``; optionally restricted to points
        whose label is in ``allowed_labels`` (ConditionalBallTree.findMaximumInnerProducts
        analogue for the conditional-KNN path)."""
        if len(self.centers) == 0:
            return [], []
        q = np.asarray(q, dtype=np.float64)
        heap: List[tuple] = []  # max-heap via negated distance

        def visit(node: int):
            center_d = np.linalg.norm(q - self.centers[node])
            if len(heap) == k and center_d - self.radii[node] > -heap[0][0]:
                return
            if self.lefts[node] == -1:
                idx = self.index[self.starts[node]:self.ends[node]]
                if allowed_labels is not None:
                    mask = np.isin(self.labels[idx], list(allowed_labels))
                    idx = idx[mask]
                if len(idx) == 0:
                    return
                d = np.linalg.norm(self.points[idx] - q, axis=1)
                for dist, i in zip(d, idx):
                    if len(heap) < k:
                        heapq.heappush(heap, (-dist, int(i)))
                    elif dist < -heap[0][0]:
                        heapq.heapreplace(heap, (-dist, int(i)))
                return
            l, r = int(self.lefts[node]), int(self.rights[node])
            dl = np.linalg.norm(q - self.centers[l])
            dr = np.linalg.norm(q - self.centers[r])
            first, second = (l, r) if dl <= dr else (r, l)
            visit(first)
            visit(second)

        visit(0)
        pairs = sorted([(-nd, i) for nd, i in heap])
        return [i for _, i in pairs], [d for d, _ in pairs]
