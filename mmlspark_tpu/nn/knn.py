"""Exact KNN estimators.

Parity surface: ``KNN:48``/``KNNModel:78`` and
``ConditionalKNN:31``/``ConditionalKNNModel`` (reference
``core/.../nn/KNN.scala``), which fit a (Conditional)BallTree and emit, per
query row, the k best matches as structs {value, distance(, label)}.

TPU-first: unconditional bulk queries run as one jitted brute-force
``‖q−x‖² = ‖q‖²+‖x‖²−2q·x`` + ``lax.top_k`` — the pairwise term is a single
MXU matmul, which beats tree traversal on TPU for any corpus that fits HBM.
Conditional queries (per-row label filters) use the host ball tree.
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, HasFeaturesCol, HasOutputCol, Param
from ..core.pipeline import Estimator, Model
from .balltree import BallTree

__all__ = ["KNN", "KNNModel", "ConditionalKNN", "ConditionalKNNModel"]


def _features_matrix(df: DataFrame, col: str) -> np.ndarray:
    # deliberate host-side float64 (exact distances for tie-stable top-k);
    # the device path in brute_force_knn casts to float32 at jnp.asarray
    vals = df[col]
    if vals.dtype == object:
        # tpulint: disable=TPU004 — host-exact f64, cast f32 before device
        return np.stack([np.asarray(v, dtype=np.float64).ravel()
                         for v in vals])
    # tpulint: disable=TPU004 — host-exact f64, cast f32 before device
    return np.asarray(vals, dtype=np.float64).reshape(len(df), -1)

_BRUTE_KNN = None


def _brute_knn_jitted():
    # module-level cache so repeated transforms hit jax's jit cache instead
    # of recompiling per call
    global _BRUTE_KNN
    if _BRUTE_KNN is None:
        import functools

        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnums=2)
        def run(C, Q, k):
            c2 = jnp.sum(C * C, axis=1)
            q2 = jnp.sum(Q * Q, axis=1)
            d2 = q2[:, None] + c2[None, :] - 2.0 * (Q @ C.T)  # MXU matmul
            neg, idx = jax.lax.top_k(-d2, k)
            return idx, jnp.sqrt(jnp.maximum(-neg, 0.0))

        _BRUTE_KNN = run
    return _BRUTE_KNN


def brute_force_knn(corpus: np.ndarray, queries: np.ndarray, k: int):
    """Batched exact top-k on device. Returns (indices, distances)."""
    import jax.numpy as jnp

    run = _brute_knn_jitted()
    idx, dist = run(jnp.asarray(corpus, jnp.float32),
                    jnp.asarray(queries, jnp.float32), int(k))
    # tpulint: disable=TPU004 — dtype-preserving drain of device outputs
    return np.asarray(idx), np.asarray(dist)


class _KNNParams(HasFeaturesCol, HasOutputCol):
    values_col = Param(str, default="values",
                       doc="column whose values are returned for matches")
    k = Param(int, default=5, doc="neighbours per query")
    leaf_size = Param(int, default=50, doc="ball tree leaf size")

    def __init__(self, **kw):
        super().__init__(**kw)
        self._set_default(output_col="output")


class KNN(Estimator, _KNNParams):
    def _fit(self, df: DataFrame) -> "KNNModel":
        X = _features_matrix(df, self.get("features_col"))
        vcol = self.get("values_col")
        values = list(df[vcol]) if vcol in df else list(range(len(df)))
        m = KNNModel()
        m.set(features_col=self.get("features_col"),
              output_col=self.get("output_col"), k=self.get("k"),
              corpus=X, values=[_json_safe(v) for v in values])
        return m


def _json_safe(v):
    return v.item() if isinstance(v, np.generic) else v


class KNNModel(Model, _KNNParams):
    corpus = ComplexParam(default=None, doc="(n, d) fitted feature matrix")
    values = Param(list, default=[], doc="per-corpus-row payload values")

    def _transform(self, df: DataFrame) -> DataFrame:
        Q = _features_matrix(df, self.get("features_col"))
        # tpulint: disable=TPU004 — corpus is the f64 host matrix from fit;
        # brute_force_knn casts to f32 before device_put
        corpus = np.asarray(self.get("corpus"))
        k = min(self.get("k"), len(corpus))
        idx, dist = brute_force_knn(corpus, Q, k)
        values = self.get("values")
        out = np.empty(len(df), dtype=object)
        for i in range(len(df)):
            out[i] = [{"value": values[j], "distance": float(d)}
                      for j, d in zip(idx[i], dist[i])]
        return df.with_column(self.get("output_col"), out)


class ConditionalKNN(Estimator, _KNNParams):
    label_col = Param(str, default="labels", doc="corpus label column")

    def _fit(self, df: DataFrame) -> "ConditionalKNNModel":
        X = _features_matrix(df, self.get("features_col"))
        vcol = self.get("values_col")
        values = list(df[vcol]) if vcol in df else list(range(len(df)))
        labels = df[self.get("label_col")]
        tree = BallTree(X, labels=labels, leaf_size=self.get("leaf_size"))
        m = ConditionalKNNModel()
        m.set(features_col=self.get("features_col"),
              output_col=self.get("output_col"), k=self.get("k"),
              label_col=self.get("label_col"),
              ball_tree=tree.to_tree(),
              values=[_json_safe(v) for v in values])
        return m


class ConditionalKNNModel(Model, _KNNParams):
    label_col = Param(str, default="labels", doc="corpus label column")
    conditioner_col = Param(str, default="conditioner",
                            doc="query column holding the allowed-label set")
    ball_tree = ComplexParam(default=None, doc="serialized BallTree arrays")
    values = Param(list, default=[], doc="per-corpus-row payload values")

    def _transform(self, df: DataFrame) -> DataFrame:
        tree = BallTree.from_tree(self.get("ball_tree"))
        Q = _features_matrix(df, self.get("features_col"))
        conds = (df[self.get("conditioner_col")]
                 if self.get("conditioner_col") in df else [None] * len(df))
        values = self.get("values")
        k = self.get("k")
        out = np.empty(len(df), dtype=object)
        for i in range(len(df)):
            allowed = None if conds[i] is None else set(
                _json_safe(c) for c in np.atleast_1d(conds[i]))
            idx, dist = tree.query(Q[i], k=k, allowed_labels=allowed)
            out[i] = [{"value": values[j], "distance": float(d),
                       "label": _json_safe(tree.labels[j])}
                      for j, d in zip(idx, dist)]
        return df.with_column(self.get("output_col"), out)
