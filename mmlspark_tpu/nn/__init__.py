from .balltree import BallTree
from .knn import (KNN, ConditionalKNN, ConditionalKNNModel, KNNModel,
                  brute_force_knn)

__all__ = ["BallTree", "KNN", "KNNModel", "ConditionalKNN",
           "ConditionalKNNModel", "brute_force_knn"]
