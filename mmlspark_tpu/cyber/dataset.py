"""Synthetic access-log generator for CyberML demos and tests.

Parity: ``synapse/ml/cyber/dataset.py`` ``DataFactory`` — two user/resource
clusters ("HR" and "FIN"); training data stays within clusters,
*intra*-cluster test pairs are unseen-but-normal, *inter*-cluster pairs are
the anomalies a fitted :class:`AccessAnomaly` should score high.
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame, object_col

__all__ = ["DataFactory"]


class DataFactory:
    def __init__(self, num_hr_users: int = 25, num_hr_resources: int = 50,
                 num_fin_users: int = 35, num_fin_resources: int = 75,
                 single_component: bool = True, seed: int = 0):
        self.hr_users = [f"hr_user_{i}" for i in range(num_hr_users)]
        self.hr_res = [f"hr_res_{i}" for i in range(num_hr_resources)]
        self.fin_users = [f"fin_user_{i}" for i in range(num_fin_users)]
        self.fin_res = [f"fin_res_{i}" for i in range(num_fin_resources)]
        self.single_component = single_component
        self.rng = np.random.default_rng(seed)
        self._train_pairs: set = set()

    def _edges(self, users, resources, density) -> list:
        out = []
        for u in users:
            n = max(1, int(density * len(resources)))
            for r in self.rng.choice(resources, size=n, replace=False):
                out.append((u, str(r), float(self.rng.integers(1, 10))))
        return out

    def _to_df(self, tups) -> DataFrame:
        return DataFrame({
            "tenant": object_col(["t0"] * len(tups)),
            "user": object_col([t[0] for t in tups]),
            "res": object_col([t[1] for t in tups]),
            "likelihood": np.array([t[2] for t in tups]),
        })

    def create_clustered_training_data(self, ratio: float = 0.25) -> DataFrame:
        tups = (self._edges(self.hr_users, self.hr_res, ratio)
                + self._edges(self.fin_users, self.fin_res, ratio))
        if self.single_component:
            # one bridging edge keeps the graph connected (so inter-cluster
            # test pairs are scored by the model rather than short-circuited
            # to +inf by the connected-components rule)
            tups.append((self.hr_users[0], self.fin_res[0], 1.0))
        self._train_pairs = {(u, r) for u, r, _ in tups}
        return self._to_df(tups)

    def _unseen(self, users, resources, n) -> list:
        out = []
        attempts = 0
        limit = 100 * n + 1000   # bounded rejection sampling: never hang
        while len(out) < n:
            attempts += 1
            if attempts > limit:
                raise ValueError(
                    f"could not draw {n} unseen pairs from a pool of "
                    f"{len(users) * len(resources)} (training covered too "
                    "much of it); lower n or the training ratio")
            u = str(self.rng.choice(users))
            r = str(self.rng.choice(resources))
            if (u, r) not in self._train_pairs:
                out.append((u, r, 0.0))
        return out

    def create_clustered_intra_test_data(self, n: int = 50) -> DataFrame:
        half = n // 2
        return self._to_df(self._unseen(self.hr_users, self.hr_res, half)
                           + self._unseen(self.fin_users, self.fin_res,
                                          n - half))

    def create_clustered_inter_test_data(self, n: int = 50) -> DataFrame:
        half = n // 2
        return self._to_df(self._unseen(self.hr_users, self.fin_res, half)
                           + self._unseen(self.fin_users, self.hr_res,
                                          n - half))
