"""Collaborative-filtering access-anomaly detection.

Parity: ``synapse/ml/cyber/anomaly/collaborative_filtering.py`` —
``AccessAnomaly`` learns per-tenant user/resource latent vectors from access
logs (Spark ALS in the reference, ``:719-780``), normalizes them so training
scores have mean 0 / std 1 per tenant (``ModelNormalizeTransformer:886``),
and scores new (user, resource) pairs by a dot product with special cases
(``AccessAnomalyModel._transform:366-411``): unknown user/resource → null,
cross connected-component pairs → +inf, optionally previously-seen pairs →
0. Lower likelihood ⇒ higher anomaly after normalization the score is
*negated likelihood z-score* exactly like the reference (low dot = unusual).

TPU-native redesign: ALS is a jitted alternating ridge solve on dense
per-tenant matrices. Each half-step builds every user's (r×r) normal matrix
with one einsum and solves them as a single batched ``jnp.linalg.solve`` —
MXU-batched linear algebra instead of a Spark shuffle. Implicit feedback
uses the Hu-Koren-Volinsky confidence trick (C = 1 + alpha·R) with the
shared ``VᵀV`` precomputation.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import Param
from ..core.pipeline import Estimator, Model
from ..core.schema import py_scalar as _py
from .complement_access import ComplementAccessTransformer
from .features import IdIndexer, LinearScalarScaler, MultiIndexer

__all__ = ["AccessAnomaly", "AccessAnomalyModel", "ConnectedComponents"]


# ---------------------------------------------------------------------------
# batched ALS (the Spark-ALS replacement)
# ---------------------------------------------------------------------------

def _als(R: np.ndarray, M: np.ndarray, rank: int, iters: int, reg: float,
         implicit: bool, alpha: float, seed: int):
    """R (n_users, n_res) ratings, M mask of observed. Returns (U, V)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    n_u, n_r = R.shape
    U0 = jnp.asarray(rng.normal(0, 0.1, (n_u, rank)), jnp.float32)
    V0 = jnp.asarray(rng.normal(0, 0.1, (n_r, rank)), jnp.float32)
    Rd = jnp.asarray(R, jnp.float32)
    Md = jnp.asarray(M, jnp.float32)
    eye = jnp.eye(rank, dtype=jnp.float32) * reg

    def solve_side(X, R, M):
        """Solve for the other side's factors given X (n_x, r)."""
        if implicit:
            # C = 1 + alpha R on observed; preference p = M
            XtX = X.T @ X                                   # (r, r) shared
            CmI = alpha * R * M                             # (n_y, n_x) extra conf
            A = XtX[None] + jnp.einsum("yx,xi,xj->yij", CmI, X, X) + eye
            b = ((1.0 + CmI) * M) @ X                       # (n_y, r)
        else:
            A = jnp.einsum("yx,xi,xj->yij", M, X, X) + eye
            b = (R * M) @ X
        return jnp.linalg.solve(A, b[..., None])[..., 0]

    def step(carry, _):
        U, V = carry
        U = solve_side(V, Rd, Md)                # users: rows index users
        V = solve_side(U, Rd.T, Md.T)            # items
        return (U, V), None

    (U, V), _ = jax.lax.scan(step, (U0, V0), None, length=iters)
    return np.asarray(U), np.asarray(V)


class ConnectedComponents:
    """Union-find over the bipartite user-resource graph, per tenant
    (reference ``ConnectedComponents:415-470``)."""

    @staticmethod
    def components(users, resources):
        parent: Dict = {}

        def find(x):
            while parent.setdefault(x, x) != x:
                parent[x] = parent[parent[x]]
                x = parent[x]
            return x

        for u, r in zip(users, resources):
            ru, rr = find(("u", u)), find(("r", r))
            if ru != rr:
                parent[ru] = rr
        user_comp = {u: find(("u", u)) for u in set(users)}
        res_comp = {r: find(("r", r)) for r in set(resources)}
        # canonical integer ids
        ids = {c: i for i, c in enumerate(
            dict.fromkeys(list(user_comp.values()) + list(res_comp.values())))}
        return ({u: ids[c] for u, c in user_comp.items()},
                {r: ids[c] for r, c in res_comp.items()})


class AccessAnomaly(Estimator):
    """Learn normal (tenant, user, resource) access patterns; score outliers."""

    tenant_col = Param(str, default="tenant", doc="tenant column")
    user_col = Param(str, default="user", doc="user column")
    res_col = Param(str, default="res", doc="resource column")
    likelihood_col = Param(str, default="likelihood",
                           doc="access count/likelihood column")
    output_col = Param(str, default="anomaly_score", doc="score column")
    rank_param = Param(int, default=10, doc="latent dimension")
    max_iter = Param(int, default=25, doc="ALS iterations")
    reg_param = Param(float, default=1.0, doc="ridge regularization")
    apply_implicit_cf = Param(bool, default=True,
                              doc="implicit-feedback ALS (confidence "
                                  "weighting) vs explicit with sampled "
                                  "negatives")
    alpha_param = Param(float, default=1.0, doc="implicit confidence slope")
    low_value = Param(float, default=5.0, doc="likelihood rescale lower bound")
    high_value = Param(float, default=10.0, doc="likelihood rescale upper bound")
    complementset_factor = Param(int, default=2,
                                 doc="negative samples per row (explicit mode)")
    neg_score = Param(float, default=1.0, doc="rating for sampled negatives")
    seed = Param(int, default=0, doc="init/sampling seed")

    def _fit(self, df: DataFrame) -> "AccessAnomalyModel":
        tcol, ucol, rcol = (self.get("tenant_col"), self.get("user_col"),
                            self.get("res_col"))
        lcol = self.get("likelihood_col")
        rank = self.get("rank_param")

        indexer = MultiIndexer([
            IdIndexer(input_col=ucol, output_col="__uidx__",
                      partition_key=tcol, reset_per_partition=True),
            IdIndexer(input_col=rcol, output_col="__ridx__",
                      partition_key=tcol, reset_per_partition=True),
        ])
        ix_model = indexer.fit(df)
        idf = ix_model.transform(df)

        if lcol in df.columns:
            scaled = LinearScalarScaler(
                input_col=lcol, output_col="__scaled__", partition_key=tcol,
                min_required_value=self.get("low_value"),
                max_required_value=self.get("high_value")).fit(idf) \
                .transform(idf)
        else:
            scaled = idf.with_column("__scaled__",
                                     np.full(len(idf), self.get("high_value")))

        tenants = scaled[tcol]
        user_maps: Dict = {}
        res_maps: Dict = {}
        stats: Dict = {}
        seen: Dict = {}
        comps: Dict = {}
        for t_raw in dict.fromkeys(tenants):
            t = _py(t_raw)   # plain scalar: keys must survive JSON save
            mask = tenants == t_raw
            sub_u = scaled["__uidx__"][mask]
            sub_r = scaled["__ridx__"][mask]
            sub_s = scaled["__scaled__"][mask].astype(np.float64)
            n_u, n_r = int(sub_u.max()), int(sub_r.max())
            R = np.zeros((n_u, n_r), np.float64)
            M = np.zeros((n_u, n_r), np.float64)
            # duplicate (user, res) rows accumulate (order-independent) —
            # repeated accesses add confidence rather than last-write-wins
            np.add.at(R, (sub_u - 1, sub_r - 1), sub_s)
            M[sub_u - 1, sub_r - 1] = 1.0
            if not self.get("apply_implicit_cf"):
                # explicit mode: sampled complement accesses as negatives
                comp = ComplementAccessTransformer(
                    partition_key=None,
                    indexed_col_names=["__uidx__", "__ridx__"],
                    complementset_factor=self.get("complementset_factor"),
                    seed=self.get("seed")).transform(
                        DataFrame({"__uidx__": sub_u, "__ridx__": sub_r}))
                cu = comp["__uidx__"] - 1
                cr = comp["__ridx__"] - 1
                R[cu, cr] = self.get("neg_score")
                M[cu, cr] = 1.0
            U, V = _als(R, M, rank, self.get("max_iter"),
                        self.get("reg_param"),
                        self.get("apply_implicit_cf"),
                        self.get("alpha_param"), self.get("seed"))
            # normalization (ModelNormalizeTransformer parity): training
            # scores → mean 0 / std 1 per tenant, folded into the factors
            train_scores = np.einsum("ij,ij->i", U[sub_u - 1], V[sub_r - 1])
            mu, sd = float(train_scores.mean()), float(train_scores.std())
            sd = sd if sd > 1e-12 else 1.0
            stats[t] = (mu, sd)

            # raw id → vector maps (names converted once, reused thrice)
            us = [_py(x) for x in df[ucol][mask]]
            rs = [_py(x) for x in df[rcol][mask]]
            user_maps[t] = {name: U[int(idx) - 1]
                            for name, idx in zip(us, sub_u)}
            res_maps[t] = {name: V[int(idx) - 1]
                           for name, idx in zip(rs, sub_r)}
            seen[t] = set(zip(us, rs))
            comps[t] = ConnectedComponents.components(us, rs)

        m = AccessAnomalyModel()
        m.set(tenant_col=tcol, user_col=ucol, res_col=rcol,
              output_col=self.get("output_col"))
        m._state = {"user_maps": user_maps, "res_maps": res_maps,
                    "stats": stats, "seen": seen, "comps": comps}
        return m


class AccessAnomalyModel(Model):
    """Scores = z-normalized *negative* likelihood: higher ⇒ more anomalous."""

    tenant_col = Param(str, default="tenant", doc="tenant column")
    user_col = Param(str, default="user", doc="user column")
    res_col = Param(str, default="res", doc="resource column")
    output_col = Param(str, default="anomaly_score", doc="score column")
    preserve_history = Param(bool, default=True,
                             doc="seen (tenant,user,res) triples score 0")

    #: fitted state (maps/stats); persisted via _save_extra
    _state: Optional[dict] = None

    def _transform(self, df: DataFrame) -> DataFrame:
        s = self._state
        assert s is not None, "model has no fitted state"
        tcol, ucol, rcol = (self.get("tenant_col"), self.get("user_col"),
                            self.get("res_col"))
        out = np.empty(len(df), dtype=object)
        for i, (t, u, r) in enumerate(zip(df[tcol], df[ucol], df[rcol])):
            t, u, r = _py(t), _py(u), _py(r)
            umap = s["user_maps"].get(t, {})
            rmap = s["res_maps"].get(t, {})
            if self.get("preserve_history") and (u, r) in s["seen"].get(t, ()):
                out[i] = 0.0
                continue
            uv, rv = umap.get(u), rmap.get(r)
            if uv is None or rv is None:
                out[i] = None
                continue
            ucomp, rcomp = s["comps"][t]
            if ucomp.get(u) != rcomp.get(r):
                out[i] = float("inf")
                continue
            mu, sd = s["stats"][t]
            likelihood_z = (float(np.dot(uv, rv)) - mu) / sd
            out[i] = -likelihood_z   # low likelihood ⇒ high anomaly
        return df.with_column(self.get("output_col"), out)

    # -- persistence of the fitted maps --------------------------------------
    def _save_extra(self, path: str) -> None:
        import json
        import os
        s = self._state
        blob = {
            "stats": [[t, mu, sd] for t, (mu, sd) in s["stats"].items()],
            "seen": [[t, sorted([list(p) for p in pairs])]
                     for t, pairs in s["seen"].items()],
            "comps": [[t, list(c[0].items()), list(c[1].items())]
                      for t, c in s["comps"].items()],
            "user_keys": [[t, list(m.keys())] for t, m in s["user_maps"].items()],
            "res_keys": [[t, list(m.keys())] for t, m in s["res_maps"].items()],
            "factors_format": "ordinal_v2",
        }
        with open(os.path.join(path, "state.json"), "w") as f:
            json.dump(blob, f)
        # arrays keyed by tenant *ordinal* (u_0, r_0, ...): tenant names can
        # contain zip-hostile characters ('/', ...); the tenant order is the
        # order of user_keys/res_keys in state.json
        arrays = {}
        for i, (t, m) in enumerate(s["user_maps"].items()):
            arrays[f"u_{i}"] = np.stack(list(m.values())) if m else np.zeros((0, 1))
        for i, (t, m) in enumerate(s["res_maps"].items()):
            arrays[f"r_{i}"] = np.stack(list(m.values())) if m else np.zeros((0, 1))
        np.savez(os.path.join(path, "factors.npz"), **arrays)

    def _load_extra(self, path: str) -> None:
        import json
        import os
        with open(os.path.join(path, "state.json")) as f:
            blob = json.load(f)
        z = np.load(os.path.join(path, "factors.npz"))
        s = {"user_maps": {}, "res_maps": {}, "stats": {}, "seen": {},
             "comps": {}}
        for t, mu, sd in blob["stats"]:
            s["stats"][t] = (mu, sd)
        for t, pairs in blob["seen"]:
            s["seen"][t] = set(tuple(p) for p in pairs)
        for t, uc, rc in blob["comps"]:
            s["comps"][t] = (dict((k, v) for k, v in uc),
                             dict((k, v) for k, v in rc))
        # explicit format marker — key-presence probing would misroute legacy
        # archives whose tenant names are themselves numeric strings
        ordinal = blob.get("factors_format") == "ordinal_v2"
        for j, (t, keys) in enumerate(blob["user_keys"]):
            U = z[f"u_{j}"] if ordinal else z[f"u_{t}"]
            s["user_maps"][t] = {k: U[i] for i, k in enumerate(keys)}
        for j, (t, keys) in enumerate(blob["res_keys"]):
            V = z[f"r_{j}"] if ordinal else z[f"r_{t}"]
            s["res_maps"][t] = {k: V[i] for i, k in enumerate(keys)}
        self._state = s
