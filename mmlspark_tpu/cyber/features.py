"""Per-tenant feature utilities: id indexers and scalar scalers.

Parity: ``synapse/ml/cyber/feature/indexers.py`` (IdIndexer/MultiIndexer —
contiguous 1-based ids per partition key, with ``undo_transform``) and
``feature/scalers.py`` (StandardScalarScaler / LinearScalarScaler — z-score
or min-max scaling computed within each partition).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..core.dataframe import DataFrame, object_col
from ..core.params import ComplexParam, HasInputCol, HasOutputCol, Param
from ..core.pipeline import Estimator, Model
from ..core.schema import py_scalar as _py

__all__ = ["IdIndexer", "IdIndexerModel", "MultiIndexer", "MultiIndexerModel",
           "StandardScalarScaler", "StandardScalarScalerModel",
           "LinearScalarScaler", "LinearScalarScalerModel"]

_NO_TENANT = "__no_tenant__"


def _tenants(df: DataFrame, key: Optional[str]) -> np.ndarray:
    if key is None:
        return np.full(len(df), _NO_TENANT, dtype=object)
    return df[key]


class IdIndexer(Estimator, HasInputCol, HasOutputCol):
    """Map raw ids to contiguous per-tenant 1-based integer ids."""

    partition_key = Param(str, default=None, doc="tenant column (optional)")
    reset_per_partition = Param(bool, default=True,
                                doc="ids restart at 1 within each tenant "
                                    "(vs globally contiguous)")

    def _fit(self, df: DataFrame) -> "IdIndexerModel":
        key = self.get_or_none("partition_key")
        tenants = _tenants(df, key)
        vals = df[self.get("input_col")]
        vocab: Dict = {}
        # store plain Python scalars so the fitted vocab is JSON-serializable
        if self.get("reset_per_partition"):
            counters: Dict = {}
            for t, v in zip(tenants, vals):
                t, v = _py(t), _py(v)
                if (t, v) not in vocab:
                    counters[t] = counters.get(t, 0) + 1
                    vocab[(t, v)] = counters[t]
        else:
            nxt = 1
            for t, v in zip(tenants, vals):
                t, v = _py(t), _py(v)
                if (t, v) not in vocab:
                    vocab[(t, v)] = nxt
                    nxt += 1
        m = IdIndexerModel()
        m.set(input_col=self.get("input_col"),
              output_col=self.get("output_col"), partition_key=key,
              vocab=[[t, v, i] for (t, v), i in vocab.items()])
        return m


class IdIndexerModel(Model, HasInputCol, HasOutputCol):
    partition_key = Param(str, default=None, doc="tenant column (optional)")
    vocab = ComplexParam(default=None, doc="[[tenant, value, id], ...]")

    def _lookup(self) -> Dict:
        return {(t, v): i for t, v, i in self.get("vocab")}

    def _transform(self, df: DataFrame) -> DataFrame:
        lut = self._lookup()
        tenants = _tenants(df, self.get_or_none("partition_key"))
        vals = df[self.get("input_col")]
        out = np.array([lut.get((_py(t), _py(v)), 0)
                        for t, v in zip(tenants, vals)],
                       dtype=np.int64)   # 0 = unseen id
        return df.with_column(self.get("output_col"), out)

    def undo_transform(self, df: DataFrame) -> DataFrame:
        """Indexed ids → original values (reference ``undo_transform``)."""
        inv = {(t, i): v for t, v, i in self.get("vocab")}
        tenants = _tenants(df, self.get_or_none("partition_key"))
        idx = df[self.get("output_col")]
        vals = object_col([inv.get((_py(t), int(i)))
                           for t, i in zip(tenants, idx)])
        return df.with_column(self.get("input_col"), vals)


class MultiIndexer(Estimator):
    """Fit several IdIndexers at once (reference ``MultiIndexer``)."""

    indexers = ComplexParam(default=[], doc="list of IdIndexer stages")

    def __init__(self, indexers: Optional[List[IdIndexer]] = None, **kw):
        super().__init__(**kw)
        if indexers is not None:
            self.set(indexers=list(indexers))

    def _fit(self, df: DataFrame) -> "MultiIndexerModel":
        models = [ix.fit(df) for ix in self.get("indexers")]
        m = MultiIndexerModel()
        m.set(models=models)
        return m


class MultiIndexerModel(Model):
    models = ComplexParam(default=[], doc="fitted IdIndexerModels")

    def _transform(self, df: DataFrame) -> DataFrame:
        for m in self.get("models"):
            df = m.transform(df)
        return df

    def get_model_by_input_col(self, input_col: str) -> Optional[IdIndexerModel]:
        for m in self.get("models"):
            if m.get("input_col") == input_col:
                return m
        return None


# ---------------------------------------------------------------------------
# scalers
# ---------------------------------------------------------------------------

class _ScalerBase(Estimator, HasInputCol, HasOutputCol):
    partition_key = Param(str, default=None, doc="tenant column (optional)")

    def _group_stats(self, df: DataFrame):
        key = self.get_or_none("partition_key")
        tenants = _tenants(df, key)
        vals = df[self.get("input_col")].astype(np.float64)
        stats = {}
        for t in dict.fromkeys(tenants):
            stats[t] = self._stat(vals[tenants == t])
        return [[t, *s] for t, s in stats.items()]


class StandardScalarScaler(_ScalerBase):
    """Per-tenant z-score (reference ``StandardScalarScaler``)."""

    coefficient_factor = Param(float, default=1.0,
                               doc="multiplier applied after standardization")

    def _stat(self, v):
        return [float(v.mean()), float(v.std())]

    def _fit(self, df: DataFrame) -> "StandardScalarScalerModel":
        m = StandardScalarScalerModel()
        m.set(input_col=self.get("input_col"),
              output_col=self.get("output_col"),
              partition_key=self.get_or_none("partition_key"),
              per_group_stats=self._group_stats(df),
              coefficient_factor=self.get("coefficient_factor"))
        return m


class StandardScalarScalerModel(Model, HasInputCol, HasOutputCol):
    partition_key = Param(str, default=None, doc="tenant column (optional)")
    per_group_stats = ComplexParam(default=None, doc="[[tenant, mean, std]]")
    coefficient_factor = Param(float, default=1.0, doc="post multiplier")

    def _transform(self, df: DataFrame) -> DataFrame:
        stats = {t: (mu, sd) for t, mu, sd in self.get("per_group_stats")}
        tenants = _tenants(df, self.get_or_none("partition_key"))
        v = df[self.get("input_col")].astype(np.float64)
        out = np.empty(len(df))
        for i, (t, x) in enumerate(zip(tenants, v)):
            mu, sd = stats.get(t, (0.0, 1.0))
            out[i] = self.get("coefficient_factor") * (
                (x - mu) / sd if sd > 0 else 0.0)
        return df.with_column(self.get("output_col"), out)


class LinearScalarScaler(_ScalerBase):
    """Per-tenant min-max mapping to [min_required, max_required]."""

    min_required_value = Param(float, default=0.0, doc="output min")
    max_required_value = Param(float, default=1.0, doc="output max")

    def _stat(self, v):
        return [float(v.min()), float(v.max())]

    def _fit(self, df: DataFrame) -> "LinearScalarScalerModel":
        m = LinearScalarScalerModel()
        m.set(input_col=self.get("input_col"),
              output_col=self.get("output_col"),
              partition_key=self.get_or_none("partition_key"),
              per_group_stats=self._group_stats(df),
              min_required_value=self.get("min_required_value"),
              max_required_value=self.get("max_required_value"))
        return m


class LinearScalarScalerModel(Model, HasInputCol, HasOutputCol):
    partition_key = Param(str, default=None, doc="tenant column (optional)")
    per_group_stats = ComplexParam(default=None, doc="[[tenant, min, max]]")
    min_required_value = Param(float, default=0.0, doc="output min")
    max_required_value = Param(float, default=1.0, doc="output max")

    def _transform(self, df: DataFrame) -> DataFrame:
        stats = {t: (lo, hi) for t, lo, hi in self.get("per_group_stats")}
        tenants = _tenants(df, self.get_or_none("partition_key"))
        v = df[self.get("input_col")].astype(np.float64)
        lo_r = self.get("min_required_value")
        hi_r = self.get("max_required_value")
        out = np.empty(len(df))
        for i, (t, x) in enumerate(zip(tenants, v)):
            lo, hi = stats.get(t, (0.0, 1.0))
            if hi > lo:
                out[i] = lo_r + (x - lo) * (hi_r - lo_r) / (hi - lo)
            else:
                out[i] = (lo_r + hi_r) / 2.0
        return df.with_column(self.get("output_col"), out)
