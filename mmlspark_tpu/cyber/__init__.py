"""CyberML: access-anomaly detection on (tenant, user, resource) logs.

Parity surface: the reference's pure-Python ``synapse/ml/cyber`` package
(``core/src/main/python/synapse/ml/cyber/**``):

* feature: per-tenant id indexers and scalers (``feature/indexers.py``,
  ``feature/scalers.py``)
* anomaly: ``ComplementAccessTransformer`` (``anomaly/complement_access.py``)
  and the ``AccessAnomaly`` collaborative-filtering estimator
  (``anomaly/collaborative_filtering.py``)
* ``DataFactory`` demo-data generator (``dataset.py``)

TPU-native redesign: Spark ALS is replaced by a jitted alternating
least-squares in JAX — per-user/per-item ridge systems are built with
einsums and solved as one batched ``jnp.linalg.solve``, so each half-step
is a handful of large MXU ops instead of a Spark shuffle.
"""

from .features import (IdIndexer, IdIndexerModel, LinearScalarScaler,
                       LinearScalarScalerModel, MultiIndexer,
                       MultiIndexerModel, StandardScalarScaler,
                       StandardScalarScalerModel)
from .complement_access import ComplementAccessTransformer
from .access_anomaly import AccessAnomaly, AccessAnomalyModel
from .dataset import DataFactory

__all__ = [
    "IdIndexer", "IdIndexerModel", "MultiIndexer", "MultiIndexerModel",
    "StandardScalarScaler", "StandardScalarScalerModel",
    "LinearScalarScaler", "LinearScalarScalerModel",
    "ComplementAccessTransformer", "AccessAnomaly", "AccessAnomalyModel",
    "DataFactory",
]
