"""Complement-set access sampling.

Parity: ``synapse/ml/cyber/anomaly/complement_access.py`` — for each observed
(indexed) access tuple, draw ``complementset_factor`` random tuples from the
per-tenant index ranges, drop any that actually occur in the data, and return
the remainder (a sample of accesses that did NOT happen — the negatives for
explicit-feedback training).
"""

from __future__ import annotations

import numpy as np

from ..core.dataframe import DataFrame, object_col
from ..core.params import Param
from ..core.pipeline import Transformer

__all__ = ["ComplementAccessTransformer"]


class ComplementAccessTransformer(Transformer):
    partition_key = Param(str, default=None, doc="tenant column (optional)")
    indexed_col_names = Param((list, str), default=[],
                              doc="indexed id columns (e.g. user/res indices)")
    complementset_factor = Param(int, default=2,
                                 doc="candidate samples drawn per input row")
    seed = Param(int, default=0, doc="sampling seed")

    def _transform(self, df: DataFrame) -> DataFrame:
        factor = self.get("complementset_factor")
        cols = self.get("indexed_col_names")
        key = self.get_or_none("partition_key")
        if factor == 0 or not len(df):
            empty = {c: np.array([], dtype=np.int64) for c in cols}
            if key is not None:
                empty = {key: np.array([], dtype=object), **empty}
            return DataFrame(empty)

        tenants = (df[key] if key is not None
                   else np.zeros(len(df), dtype=np.int64))
        vals = {c: df[c].astype(np.int64) for c in cols}
        rng = np.random.default_rng(self.get("seed"))

        out_tenant, out_cols = [], {c: [] for c in cols}
        for t in dict.fromkeys(tenants):
            mask = tenants == t
            n = int(mask.sum())
            los = {c: int(vals[c][mask].min()) for c in cols}
            his = {c: int(vals[c][mask].max()) for c in cols}
            seen = set(zip(*(vals[c][mask] for c in cols)))
            cand = {c: rng.integers(los[c], his[c] + 1, n * factor)
                    for c in cols}
            kept = set()
            for row in zip(*(cand[c] for c in cols)):
                if row not in seen:
                    kept.add(row)
            for row in sorted(kept):
                out_tenant.append(t)
                for c, v in zip(cols, row):
                    out_cols[c].append(int(v))

        data = {c: np.asarray(out_cols[c], dtype=np.int64) for c in cols}
        if key is not None:
            data = {key: object_col(out_tenant), **data}
        return DataFrame(data)
