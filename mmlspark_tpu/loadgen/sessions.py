"""Long-decode session survivability drill for open-loop scenarios.

The ``decode-kill`` scenario runs live "generation sessions" alongside the
request traffic: each session is a real journal record stream (insert
record + per-tick emitted-token tails, the same ``sess``/``tail`` schema
``ContinuousDecoder`` writes) owned by one worker, with tokens produced by
a deterministic ticking stand-in — the loadgen twin of
``cluster_echo_engine``, which stands in for the model engine the same
way. When the chaos script kills the owning worker mid-decode, the drill
recovers exactly the way the serving plane does: scan the dead worker's
journal (``ServingJournal.scan_sessions``), ship the live sessions to a
survivor over the real ``/_adopt`` control hop, and resume emission from
the journaled tail. The scorecard gains ``sessions_lost`` /
``sessions_recovered`` / ``recovery_p99_ms``, and a session counts as
lost unless its final token stream is *identical* to the uninterrupted
run's — the same token-parity bar the real-decoder failover tests
(``tests/test_session_failover.py``) hold the warm/cold paths to.

Serving-plane imports live inside methods, matching ``scenarios.py``: the
plan/describe half of loadgen stays importable with nothing but the
stdlib.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
import zlib
from typing import Dict, List, Optional

__all__ = ["SessionDrill", "session_token"]


def session_token(session_id: str, index: int) -> int:
    """The deterministic token stream: token ``index`` of ``session_id``.
    Pure, so the expected uninterrupted stream is computable without
    running anything — token parity after a failover is an equality
    check, not a statistical one."""
    return zlib.crc32(f"{session_id}:{index}".encode()) % 997


class SessionDrill:
    """Run ``n_sessions`` journal-backed decode sessions against a
    ``ServingCluster``, surviving mid-run worker kills.

    Lifecycle: :meth:`start` assigns sessions round-robin over the
    cluster's workers (journaling the insert record write-ahead), a
    ticker thread emits one token per live session per tick (journaling
    the tail), and :meth:`finish` waits for every session to complete,
    then returns the ``sessions`` scorecard block. Worker death is
    detected by incarnation change (``restart_worker`` replaces the
    object under the same id) or a closed server; recovery replays the
    dead incarnation's journal onto a survivor via ``/_adopt``.
    """

    def __init__(self, cluster, *, n_sessions: int,
                 tokens_per_session: int = 24,
                 tick_s: float = 0.02,
                 journal_dir: Optional[str] = None):
        self.cluster = cluster
        self.n_sessions = int(n_sessions)
        self.tokens_per_session = int(tokens_per_session)
        self.tick_s = float(tick_s)
        self._dir = journal_dir or tempfile.mkdtemp(prefix="session-drill-")
        self._lock = threading.Lock()
        #: guards the journal map alone — taken inside ``_journal_for``,
        #: which runs both on the ticker and on adopt-handler HTTP threads
        self._jlock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: sid → {"worker", "incarnation", "emitted", "done", "recovered"}
        self._sessions: Dict[str, dict] = {}
        #: drill-owned per-worker journals, keyed by worker id — separate
        #: files from the server's request journal so the drill runs
        #: against clusters constructed without ``journal_dir``
        self._journals: Dict[str, object] = {}
        self._journal_paths: Dict[str, str] = {}
        self._recovery_s: List[float] = []

    # -- journal plumbing ---------------------------------------------------
    def _journal_for(self, worker_id: str):
        from ..serving.journal import ServingJournal
        with self._jlock:
            j = self._journals.get(worker_id)
            if j is None or j.closed:
                path = os.path.join(self._dir, f"{worker_id}.sessions")
                self._journal_paths[worker_id] = path
                j = ServingJournal(path, fsync=False)
                self._journals[worker_id] = j
            return j

    def _worker(self, worker_id: str):
        for w in self.cluster.workers:
            if w.worker_id == worker_id:
                return w
        return None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "SessionDrill":
        workers = list(self.cluster.workers)
        for w in workers:
            w.adopt_handler = self._make_adopt_handler(w)
        for k in range(self.n_sessions):
            w = workers[k % len(workers)]
            sid = f"decode-{k}"
            # write-ahead insert record, exactly like ContinuousDecoder
            # .submit: the session is recoverable before it is runnable
            self._journal_for(w.worker_id).record_session(
                sid, [k], {"max_new": self.tokens_per_session,
                           "temperature": 0.0, "seed": k})
            with self._lock:
                self._sessions[sid] = {
                    "worker": w.worker_id, "incarnation": id(w),
                    "emitted": [], "done": False, "recovered": False}
        self._thread = threading.Thread(target=self._run,
                                        name="session-drill", daemon=True)
        self._thread.start()
        return self

    def _make_adopt_handler(self, worker):
        def handler(payload: dict) -> dict:
            adopted = 0
            for entry in payload.get("sessions") or []:
                sess = entry.get("session") or {}
                sid = str(sess.get("id") or "")
                if not sid:
                    continue
                emitted = [int(t) for t in sess.get("emitted") or []]
                # re-journal the canonical form on the adopter first —
                # a second failure before the next tick must still find
                # the session whole
                j = self._journal_for(worker.worker_id)
                j.record_session(sid, sess.get("prompt") or [],
                                 sess.get("params") or {},
                                 phash=sess.get("phash"))
                if emitted:
                    j.record_session_tokens(sid, emitted)
                with self._lock:
                    st = self._sessions.get(sid)
                    if st is not None and not st["done"]:
                        st["worker"] = worker.worker_id
                        st["incarnation"] = id(worker)
                        st["emitted"] = emitted
                        st["recovered"] = True
                worker.adopted_sessions.append(entry)
                adopted += 1
            return {"ok": True, "adopted": adopted,
                    "mode": payload.get("mode", "cold"),
                    "worker": worker.worker_id}
        return handler

    # -- the ticker ---------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self.tick_s):
            try:
                self._tick()
            except Exception:
                # a torn tick (worker mid-restart) is the next tick's
                # problem; the drill itself must survive the chaos it runs
                continue
            with self._lock:
                if all(s["done"] for s in self._sessions.values()):
                    return

    def _tick(self) -> None:
        with self._lock:
            sids = [sid for sid, st in self._sessions.items()
                    if not st["done"]]
        dead_workers: List[str] = []
        for sid in sids:
            # the whole journal-write + in-memory append is one critical
            # section: an adopt handler replacing the emitted tail (a
            # concurrent recovery landing on this worker) can only run
            # between whole tokens, never inside one
            with self._lock:
                st = self._sessions[sid]
                if st["done"]:
                    continue
                w = self._worker(st["worker"])
                if (w is None or id(w) != st["incarnation"]
                        or w.server.closed):
                    if st["worker"] not in dead_workers:
                        dead_workers.append(st["worker"])
                    continue
                tok = session_token(sid, len(st["emitted"]))
                self._journal_for(w.worker_id).record_session_tokens(
                    sid, [tok])
                st["emitted"].append(tok)
                if len(st["emitted"]) >= self.tokens_per_session:
                    st["done"] = True
                    self._journal_for(w.worker_id).record_session_end(sid)
        for wid in dead_workers:
            self._recover(wid)

    def _recover(self, worker_id: str) -> None:
        """Replay the dead incarnation's journaled sessions onto a
        survivor over the real ``/_adopt`` hop (driver-orchestrated
        failover's cold path, run drill-side because the drill owns the
        journals)."""
        from ..serving.distributed import _http_json
        from ..serving.journal import ServingJournal
        t0 = time.monotonic()
        with self._jlock:
            path = self._journal_paths.get(worker_id)
            old = self._journals.pop(worker_id, None)
        if path is None:
            return
        if old is not None and not old.closed:
            old.close()
        sessions = ServingJournal.scan_sessions(path)
        with self._lock:
            wanted = {sid for sid, st in self._sessions.items()
                      if st["worker"] == worker_id and not st["done"]}
        entries = [{"session": dict(s, id=sid), "kv": None}
                   for sid, s in sessions.items() if sid in wanted]
        if not entries:
            return
        survivors = [w for w in self.cluster.workers
                     if w.worker_id != worker_id and not w.server.closed]
        if not survivors:
            return
        target = survivors[0]
        out = _http_json(target.advertised_address + "/_adopt",
                         {"sessions": entries, "mode": "cold",
                          "from": worker_id},
                         site="peer_http")
        if out.get("adopted"):
            self._recovery_s.append(time.monotonic() - t0)

    # -- results ------------------------------------------------------------
    def finish(self, timeout: float = 10.0) -> dict:
        """Wait for every session to complete (bounded), stop the ticker,
        close the drill journals, and return the scorecard block."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if all(s["done"] for s in self._sessions.values()):
                    break
            time.sleep(self.tick_s)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        with self._jlock:
            journals = list(self._journals.values())
        for j in journals:
            if not j.closed:
                j.close()
        return self.scorecard()

    def scorecard(self) -> dict:
        """``{"sessions", "lost", "recovered", "recovery_p99_ms"}`` —
        a session is LOST unless it completed with the exact deterministic
        token stream an uninterrupted run would have produced."""
        lost = recovered = 0
        with self._lock:
            for sid, st in self._sessions.items():
                expect = [session_token(sid, i)
                          for i in range(self.tokens_per_session)]
                if not st["done"] or st["emitted"] != expect:
                    lost += 1
                elif st["recovered"]:
                    recovered += 1
        from .scorecard import quantiles_ms
        q = quantiles_ms(self._recovery_s)
        return {"sessions": self.n_sessions, "lost": lost,
                "recovered": recovered,
                "recovery_p99_ms": q["p99_ms"] if q else None}
