"""Declarative scenarios: workload mix × tenant weights × arrival shape ×
chaos script, run open-loop against a ``ServingCluster``.

A :class:`Scenario` is pure data — everything needed to regenerate the
identical traffic plan from its seed. :func:`run_scenario` executes it:
senders fire at each arrival's *scheduled* instant regardless of how the
last reply went (open loop), the chaos script composes the existing
``MMLSPARK_TPU_FAULTS`` grammar with a mid-run
``ServingCluster.restart_worker``, and the run ends in one scorecard
(``loadgen.scorecard``) reconciled against the federated
``/debug/cluster`` counters.

Serving-plane imports live inside functions on purpose: ``codegen``
imports every module in the package, and the plan/describe half of this
module must stay importable with nothing but the stdlib.
"""

from __future__ import annotations

import json
import random
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, List, Optional

from .arrivals import (Arrival, TenantMix, diurnal_offsets, heavy_tail_rows,
                       poisson_offsets, weighted_choice)
from .progress import get_progress
from .scorecard import (build_scorecard, counters_snapshot,
                        merged_requests_total, quantiles_ms)

__all__ = ["SCENARIOS", "Scenario", "closed_loop_probe",
           "cluster_echo_engine", "get_scenario", "plan", "run_scenario"]

#: workload name → X-Mmlspark-Model header value (the three serving
#: archetypes the bench exercises: ONNX vision, text generation, GBDT)
WORKLOAD_MODELS: Dict[str, str] = {
    "vision": "onnx-vision",
    "generation": "textgen",
    "gbdt": "gbdt-scorer",
}


@dataclass(frozen=True)
class Scenario:
    """One named, seeded traffic + chaos recipe."""

    name: str
    description: str = ""
    duration_s: float = 2.0
    #: mean arrival rate (requests/second) across all tenants
    rate: float = 40.0
    arrival: str = "poisson"            # "poisson" | "diurnal"
    diurnal_depth: float = 0.5
    diurnal_period_s: Optional[float] = None
    seed: int = 20260808
    #: tenant → DRR weight; also pushed into the model registry so the
    #: serving plane's weighted-fair admission uses the same shares
    tenants: Dict[str, float] = field(
        default_factory=lambda: {"acme": 3.0, "beta": 1.0})
    workloads: Dict[str, float] = field(
        default_factory=lambda: {"vision": 0.5, "generation": 0.3,
                                 "gbdt": 0.2})
    size_median_rows: int = 8
    size_alpha: float = 1.6
    size_cap_rows: int = 512
    prefix_pool: int = 4
    prefix_skew: float = 1.1
    keyed_fraction: float = 0.75
    #: chaos script in the MMLSPARK_TPU_FAULTS grammar ("" = no faults)
    faults: str = ""
    #: seconds into the run to kill-and-replace one worker (None = never)
    restart_at_s: Optional[float] = None
    restart_worker: Optional[str] = None
    #: per-request deadline propagated as X-Mmlspark-Deadline; spans the
    #: whole retry envelope of one arrival
    deadline_s: float = 5.0
    max_retries: int = 3
    #: long-decode sessions riding the run (``loadgen.sessions``): 0 = no
    #: session drill; with a chaos restart these exercise journal-replay
    #: failover over the real ``/_adopt`` hop
    decode_sessions: int = 0
    decode_tokens: int = 24
    decode_tick_s: float = 0.02


SCENARIOS: Dict[str, Scenario] = {s.name: s for s in (
    Scenario(
        name="smoke",
        description="CI-sized deterministic mix: two tenants, Poisson "
                    "arrivals, a light seeded enqueue-fault drizzle, no "
                    "restart — bounded wall-clock, CPU-only.",
        duration_s=2.0, rate=40.0, arrival="poisson",
        faults="enqueue:error:every=7:times=6",
    ),
    Scenario(
        name="mixed-tenant-chaos",
        description="Overload drill: diurnal arrivals above capacity, "
                    "heavy early enqueue faults to trip client breakers, "
                    "and a mid-run ungraceful worker restart.",
        duration_s=4.0, rate=120.0, arrival="diurnal", diurnal_depth=0.6,
        faults="enqueue:error:every=2:times=40",
        restart_at_s=1.5, restart_worker="worker-1",
    ),
    Scenario(
        name="decode-kill",
        description="Session survivability drill: long-decode sessions "
                    "ride the traffic, one owning worker is killed "
                    "mid-decode, and every session must finish "
                    "token-identical via journal-replay failover over "
                    "/_adopt (scorecard: sessions_lost == 0).",
        duration_s=2.5, rate=30.0, arrival="poisson",
        restart_at_s=1.0, restart_worker="worker-1",
        # 40 tokens x 50ms = ~2s of decoding: the 1.0s restart lands
        # mid-stream, so worker-1's sessions MUST take the failover path
        decode_sessions=6, decode_tokens=40, decode_tick_s=0.05,
    ),
)}


def get_scenario(name: str, **overrides) -> Scenario:
    """Look up a registered scenario, optionally overriding fields
    (``get_scenario("smoke", duration_s=1.0, rate=20)``)."""
    try:
        base = SCENARIOS[name]
    except KeyError:
        raise KeyError(f"unknown scenario {name!r} "
                       f"(have: {', '.join(sorted(SCENARIOS))})") from None
    return replace(base, **overrides) if overrides else base


def plan(scenario: Scenario) -> List[Arrival]:
    """Expand a scenario into its full arrival plan — every request's
    scheduled send offset, tenant, workload, size, and prefix key. Pure
    and seeded: the same scenario always yields the identical plan."""
    rng = random.Random(scenario.seed)
    if scenario.arrival == "diurnal":
        offsets = diurnal_offsets(scenario.rate, scenario.duration_s, rng,
                                  period_s=scenario.diurnal_period_s,
                                  depth=scenario.diurnal_depth)
    else:
        offsets = poisson_offsets(scenario.rate, scenario.duration_s, rng)
    mix = TenantMix(scenario.tenants, prefix_pool=scenario.prefix_pool,
                    prefix_skew=scenario.prefix_skew,
                    keyed_fraction=scenario.keyed_fraction)
    wl_items = sorted(scenario.workloads.items())
    out: List[Arrival] = []
    for i, at in enumerate(offsets):
        tenant, prefix = mix.pick(rng)
        out.append(Arrival(
            index=i, at=at, tenant=tenant,
            workload=weighted_choice(rng, wl_items),
            rows=heavy_tail_rows(rng, median=scenario.size_median_rows,
                                 alpha=scenario.size_alpha,
                                 cap=scenario.size_cap_rows),
            prefix=prefix))
    return out


# -- serving-side helpers -----------------------------------------------------

def cluster_echo_engine(cluster, stop: threading.Event, *,
                        service_s: float = 0.0,
                        batch: int = 16) -> threading.Thread:
    """Start a model-engine stand-in: drain the cluster's request queue
    and answer 200 with a small JSON echo, optionally holding each batch
    for ``service_s`` (the knob that turns an open-loop scenario into a
    saturation drill). Returns the started daemon thread."""
    from ..io.http.schema import (EntityData, HTTPResponseData,
                                  StatusLineData)

    def loop() -> None:
        while not stop.is_set():
            got = cluster.get_batch(batch, timeout=0.02)
            if not got:
                continue
            if service_s > 0:
                time.sleep(service_s)
            for owner_id, cached in got:
                body = json.dumps({"ok": True, "rid": cached.request_id})
                resp = HTTPResponseData(
                    entity=EntityData.from_string(body),
                    status_line=StatusLineData(status_code=200))
                try:
                    cluster.reply(owner_id, cached.request_id, resp)
                except Exception:
                    # the owner died mid-flight (chaos restart): the
                    # client's retry loop owns recovery, not the engine
                    pass

    t = threading.Thread(target=loop, name="scenario-echo-engine",
                         daemon=True)
    t.start()
    return t


def _arrival_headers(scenario: Scenario, a: Arrival, deadline) -> dict:
    from ..reliability import DEADLINE_HEADER
    from ..serving.kv_pool import AFFINITY_HEADER
    headers = {
        "Content-Type": "application/json",
        "X-Mmlspark-Tenant": a.tenant,
        "X-Mmlspark-Model": WORKLOAD_MODELS.get(a.workload, a.workload),
        DEADLINE_HEADER: deadline.header_value(),
    }
    if a.prefix:
        headers[AFFINITY_HEADER] = a.prefix
    return headers


def _send_once(url: str, body: bytes, headers: dict, timeout: float):
    """One HTTP attempt. Returns ``("ok"|"shed"|"error", retry_after)``
    where ``retry_after`` is the parsed 429 Retry-After hint (None when
    absent — e.g. a 429 relayed through a forwarder, which drops
    headers)."""
    req = urllib.request.Request(url, data=body, headers=headers,
                                 method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            resp.read()
        return "ok", None
    except urllib.error.HTTPError as e:
        try:
            e.read()
        except Exception:
            pass
        if e.code == 429:
            ra = e.headers.get("Retry-After") if e.headers else None
            try:
                return "shed", (float(ra) if ra is not None else None)
            except (TypeError, ValueError):
                return "shed", None
        return "error", None
    except Exception:
        return "error", None


def _drive_arrival(scenario: Scenario, a: Arrival, t0: float,
                   targets: List[str], breakers: Dict[str, object]) -> dict:
    """Send one planned arrival to completion: scheduled-time pacing,
    Retry-After-honoring retries, deadline propagation, client-side
    breaker accounting. Always returns a sample dict — a planned arrival
    can end ok/shed/error but never vanish."""
    from ..reliability import Deadline

    scheduled = t0 + a.at
    now = time.monotonic()
    if scheduled > now:
        time.sleep(scheduled - now)
    send_lag = max(time.monotonic() - scheduled, 0.0)
    get_progress().note_sent()

    deadline = Deadline.after(scenario.deadline_s)
    body = json.dumps({"workload": a.workload, "rows": a.rows,
                       "tenant": a.tenant, "index": a.index}).encode()
    attempts = 0
    honored = 0
    outcome = "error"
    first_send = time.monotonic()
    while True:
        # prefer a target whose breaker admits the call; if every breaker
        # is open, send anyway — an open-loop generator sheds accuracy,
        # never requests (zero-lost invariant)
        pick = None
        for off in range(len(targets)):
            cand = targets[(a.index + attempts + off) % len(targets)]
            if breakers[cand].allow():
                pick = cand
                break
        if pick is None:
            pick = targets[(a.index + attempts) % len(targets)]
        attempts += 1
        timeout = max(deadline.cap(2.0), 0.05)
        outcome, retry_after = _send_once(
            pick, body, _arrival_headers(scenario, a, deadline), timeout)
        br = breakers[pick]
        if outcome == "error":
            br.record_failure()
        else:
            # a 429 is the server doing its job, not a broken peer
            br.record_success()
        if outcome == "ok" or attempts > scenario.max_retries \
                or deadline.expired:
            break
        if outcome == "shed":
            if retry_after is not None:
                honored += 1
                time.sleep(max(min(retry_after, deadline.remaining(),
                                   1.0), 0.0))
            else:
                time.sleep(min(0.02 * attempts, 0.1))
        else:
            time.sleep(min(0.01 * attempts, 0.05))
    done = time.monotonic()
    get_progress().note_done(outcome, retries=attempts - 1,
                             at_s=done - t0,
                             lat_s=done - scheduled)
    return {
        "index": a.index, "tenant": a.tenant, "workload": a.workload,
        "rows": a.rows, "outcome": outcome, "attempts": attempts,
        # scheduled arrival offset from scenario start — the timeline
        # sub-record buckets by this (scorecard.build_timeline)
        "at": round(a.at, 6),
        "honored_retries": honored, "send_lag_s": round(send_lag, 6),
        "sched_lat_s": round(done - scheduled, 6),
        "send_lat_s": round(done - first_send, 6),
    }


def closed_loop_probe(scenario: Scenario, targets: List[str],
                      n: int = 40) -> dict:
    """The regime the scorecard exists to dethrone: send → wait → send,
    latency measured from the actual send. Its p99 structurally cannot
    see queueing delay (each reply throttles the next request), which is
    exactly what the open/closed comparison in the scorecard shows.
    Runs with chaos disabled so both numbers share a workload, not a
    fault schedule."""
    from ..reliability import Deadline

    rng = random.Random(scenario.seed + 1)
    mix = TenantMix(scenario.tenants, prefix_pool=scenario.prefix_pool,
                    prefix_skew=scenario.prefix_skew,
                    keyed_fraction=scenario.keyed_fraction)
    wl_items = sorted(scenario.workloads.items())
    lats: List[float] = []
    ok = 0
    for i in range(n):
        tenant, prefix = mix.pick(rng)
        a = Arrival(index=i, at=0.0, tenant=tenant,
                    workload=weighted_choice(rng, wl_items),
                    rows=heavy_tail_rows(
                        rng, median=scenario.size_median_rows,
                        alpha=scenario.size_alpha,
                        cap=scenario.size_cap_rows),
                    prefix=prefix)
        deadline = Deadline.after(scenario.deadline_s)
        body = json.dumps({"workload": a.workload, "rows": a.rows,
                           "tenant": a.tenant, "index": i}).encode()
        start = time.monotonic()
        outcome, _ = _send_once(
            targets[i % len(targets)], body,
            _arrival_headers(scenario, a, deadline),
            max(deadline.cap(2.0), 0.05))
        lats.append(time.monotonic() - start)
        if outcome == "ok":
            ok += 1
    return {"loop_mode": "closed", "n": n, "ok": ok,
            "latency_ms": quantiles_ms(lats)}


def _fetch_json(url: str, timeout: float = 5.0) -> Optional[dict]:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except Exception:
        return None


def run_scenario(scenario: Scenario, cluster, *,
                 closed_loop_n: int = 40,
                 senders: int = 16,
                 mesh_shape: Optional[str] = None,
                 kv_dtype: Optional[str] = None,
                 store=None, harvest: bool = True,
                 log: Optional[Callable[[str], None]] = None) -> dict:
    """Run one scenario end-to-end against a live ``ServingCluster`` and
    return its scorecard.

    Order of operations: push tenant weights into the model registry →
    closed-loop probe (chaos off — the comparison baseline) → snapshot
    counters → arm the fault script → open-loop drive with the chaos
    timer running → clear faults → fetch ``/debug/costs`` (harvests
    ``cost_ledger`` rows server-side) → quiesce, heartbeat every worker,
    and read the driver's federated ``/debug/cluster`` for the exact
    reconciliation → build + harvest the scorecard.
    """
    from ..observability.slo import get_tracker
    from ..reliability import get_injector
    from ..serving.registry import get_registry
    from .scorecard import harvest_slo

    say = log or (lambda _msg: None)
    registry = get_registry()
    for tenant, weight in scenario.tenants.items():
        registry.set_tenant(tenant, weight)

    targets = [w.server.address.rstrip("/") + "/" for w in cluster.workers]
    arrivals = plan(scenario)
    progress = get_progress()
    progress.begin(scenario.name, len(arrivals),
                   duration_s=scenario.duration_s)

    say(f"closed-loop probe ({closed_loop_n} requests)")
    closed = closed_loop_probe(scenario, targets, n=closed_loop_n)

    from ..reliability import CircuitBreaker
    breakers = {t: CircuitBreaker(peer=f"loadgen:{t}", window=8,
                                  min_calls=3, failure_ratio=0.5,
                                  open_seconds=0.25) for t in targets}
    before = counters_snapshot()
    injector = get_injector()
    if scenario.faults:
        injector.configure(scenario.faults)

    chaos_timer: Optional[threading.Timer] = None
    if scenario.restart_at_s is not None and scenario.restart_worker:
        def _restart() -> None:
            say(f"chaos: restarting {scenario.restart_worker}")
            try:
                cluster.restart_worker(scenario.restart_worker)
            except Exception:
                pass
        chaos_timer = threading.Timer(scenario.restart_at_s, _restart)
        chaos_timer.daemon = True
        chaos_timer.start()

    drill = None
    if scenario.decode_sessions > 0:
        from .sessions import SessionDrill
        say(f"session drill: {scenario.decode_sessions} decode sessions "
            f"x {scenario.decode_tokens} tokens")
        drill = SessionDrill(
            cluster, n_sessions=scenario.decode_sessions,
            tokens_per_session=scenario.decode_tokens,
            tick_s=scenario.decode_tick_s).start()

    say(f"open-loop drive: {len(arrivals)} arrivals over "
        f"{scenario.duration_s:.1f}s")
    samples: List[Optional[dict]] = [None] * len(arrivals)
    next_idx = [0]
    idx_lock = threading.Lock()
    t0 = time.monotonic() + 0.05

    def sender() -> None:
        while True:
            with idx_lock:
                i = next_idx[0]
                if i >= len(arrivals):
                    return
                next_idx[0] = i + 1
            # worker addresses can change under chaos: refresh per send
            live = [w.server.address.rstrip("/") + "/"
                    for w in cluster.workers]
            for t in live:
                if t not in breakers:
                    breakers[t] = CircuitBreaker(
                        peer=f"loadgen:{t}", window=8, min_calls=3,
                        failure_ratio=0.5, open_seconds=0.25)
            samples[i] = _drive_arrival(scenario, arrivals[i], t0, live,
                                        breakers)

    # tpulint: disable=TPU025 — bounded sender pool, joined before the
    # scenario returns; a crash surfaces as missing samples in the
    # reconciliation counters, and supervisor backoff/restart would
    # distort the open-loop arrival schedule the scenario measures
    threads = [threading.Thread(target=sender, name=f"scenario-send-{k}",
                                daemon=True)
               for k in range(max(1, min(senders, len(arrivals) or 1)))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    window_s = max(time.monotonic() - t0,
                   arrivals[-1].at if arrivals else 0.0, 1e-9)
    if chaos_timer is not None:
        chaos_timer.cancel()
    injector.clear()

    sessions = None
    if drill is not None:
        sessions = drill.finish(
            timeout=max(scenario.duration_s * 2.0,
                        scenario.decode_tokens * scenario.decode_tick_s
                        * 4.0, 5.0))
        say(f"session drill: lost={sessions['lost']} "
            f"recovered={sessions['recovered']} "
            f"recovery_p99={sessions['recovery_p99_ms']}ms")

    # server-side harvest of cost_ledger rows + tenant cost join
    costs = _fetch_json(targets[0].rstrip("/") + "/debug/costs")

    # quiesce, then heartbeat every worker so the driver's federated
    # counters all describe the same instant — the exact-reconciliation
    # contract the federation tests pin down
    time.sleep(0.25)
    for w in cluster.workers:
        try:
            w.heartbeat()
        except Exception:
            pass
    after = counters_snapshot()
    cluster_view: Optional[dict] = None
    merged = None
    debug = _fetch_json(cluster.driver.url.rstrip("/") + "/debug/cluster")
    if debug is not None:
        merged = merged_requests_total(str(debug.get("metrics", "")))
        n_workers = len(cluster.workers)
        cluster_view = {
            "workers": n_workers,
            "merged_requests_total": merged,
            "global_requests_total": after.get("serving_requests"),
            "reconciled": merged == n_workers
            * float(after.get("serving_requests", -1.0)),
        }

    card = build_scorecard(
        scenario, samples, window_s=window_s,
        counters_before=before, counters_after=after, costs=costs,
        cluster_view=cluster_view, closed_loop=closed,
        mesh_shape=mesh_shape, kv_dtype=kv_dtype, sessions=sessions)

    if harvest:
        harvested = harvest_slo(get_tracker().scorecard(), store=store)
        card["harvested"] = {"slo_rows": harvested,
                             "cost_rows_via": "/debug/costs"}
    progress.finish({"ok": card["ok"], "shed": card["shed"],
                     "errors": card["errors"], "lost": card["lost"],
                     "goodput_rps": card["goodput_rps"]})
    say(f"scorecard: ok={card['ok']} shed={card['shed']} "
        f"errors={card['errors']} lost={card['lost']}")
    return card
