"""Open-loop arrival processes: *when* requests fire, decoupled from the
server's replies.

Closed-loop benches (send → wait → send) let a slow server throttle its
own load generator, so queueing collapse hides from the latency numbers —
the coordinated-omission trap. Everything here is open-loop: an arrival
process stamps each request with its *scheduled* send time up front, and
the scorecard measures latency from that intended instant, not from
whenever the sender thread actually got around to writing bytes.

Three seeded samplers compose into a scenario's traffic shape:

* :func:`poisson_offsets` — homogeneous Poisson (exponential
  interarrivals), the memoryless baseline.
* :func:`diurnal_offsets` — inhomogeneous Poisson with a sinusoidal rate
  envelope (Lewis–Shedler thinning), the day/night load swing compressed
  into a test-sized window.
* :func:`heavy_tail_rows` — Pareto request sizes (median-parameterized),
  because production payloads are not uniform batches.

:class:`TenantMix` assigns each arrival a tenant by configured weight and
a Zipf-skewed shared-prefix key (the ``X-Mmlspark-Prefix`` affinity
header), so prefix-cache routing sees realistic hot/cold skew. All
randomness flows through one ``random.Random(seed)`` — a (seed, config)
pair always yields the identical plan.
"""

from __future__ import annotations

import bisect
import math
import random
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["Arrival", "TenantMix", "diurnal_offsets", "heavy_tail_rows",
           "interarrivals", "poisson_offsets", "weighted_choice"]


@dataclass(frozen=True)
class Arrival:
    """One planned request: everything known before any byte is sent."""

    index: int
    #: scheduled send offset in seconds from scenario start — latency is
    #: measured FROM here (coordinated-omission correction)
    at: float
    tenant: str
    workload: str          # "vision" | "generation" | "gbdt"
    rows: int              # heavy-tailed request size
    #: X-Mmlspark-Prefix affinity key, None for an unkeyed request
    prefix: Optional[str]


def poisson_offsets(rate: float, duration_s: float,
                    rng: random.Random) -> List[float]:
    """Homogeneous Poisson arrival offsets over ``[0, duration_s)``.

    Interarrivals are iid Exponential(rate): mean ``1/rate``, variance
    ``1/rate**2`` — the properties tests pin down.
    """
    if rate <= 0 or duration_s <= 0:
        return []
    out: List[float] = []
    t = 0.0
    while True:
        t += rng.expovariate(rate)
        if t >= duration_s:
            return out
        out.append(t)


def diurnal_offsets(rate: float, duration_s: float, rng: random.Random,
                    period_s: Optional[float] = None,
                    depth: float = 0.5) -> List[float]:
    """Inhomogeneous Poisson with a sinusoidal "diurnal" envelope.

    Instantaneous rate ``rate * (1 + depth * sin(2*pi*t/period_s))``,
    sampled by Lewis–Shedler thinning against the peak rate: candidates
    arrive at the peak rate and are accepted with probability
    ``rate(t)/peak``, which is exact for any bounded envelope. With the
    default ``period_s == duration_s`` the first half of the window is
    the "day" (above-mean rate) and the second half the "night".
    """
    if rate <= 0 or duration_s <= 0:
        return []
    period = period_s if period_s and period_s > 0 else duration_s
    depth = min(max(float(depth), 0.0), 1.0)
    peak = rate * (1.0 + depth)
    out: List[float] = []
    t = 0.0
    while True:
        t += rng.expovariate(peak)
        if t >= duration_s:
            return out
        lam = rate * (1.0 + depth * math.sin(2.0 * math.pi * t / period))
        if rng.random() * peak <= lam:
            out.append(t)


def interarrivals(offsets: Sequence[float]) -> List[float]:
    """Gaps between consecutive offsets (first gap measured from 0)."""
    prev = 0.0
    out: List[float] = []
    for t in offsets:
        out.append(t - prev)
        prev = t
    return out


def heavy_tail_rows(rng: random.Random, median: int = 8,
                    alpha: float = 1.6, cap: int = 4096) -> int:
    """Pareto-distributed request size (rows), parameterized by its median.

    ``P(X > x) = (xm / x) ** alpha`` with ``xm`` chosen so the median is
    ``median``; ``alpha`` in (1, 2] gives the finite-mean, infinite-ish
    variance shape real payload mixes show. Capped at ``cap`` so one
    sample cannot blow a test's memory or wall-clock.
    """
    alpha = max(float(alpha), 0.1)
    xm = float(median) / (2.0 ** (1.0 / alpha))
    u = max(rng.random(), 1e-12)
    x = xm / (u ** (1.0 / alpha))
    return max(1, min(int(math.ceil(x)), int(cap)))


def weighted_choice(rng: random.Random,
                    items: Sequence[Tuple[str, float]]) -> str:
    """One weighted draw over ``(name, weight)`` pairs (no numpy)."""
    total = sum(max(w, 0.0) for _, w in items)
    if total <= 0:
        return items[0][0]
    r = rng.random() * total
    acc = 0.0
    for name, w in items:
        acc += max(w, 0.0)
        if r <= acc:
            return name
    return items[-1][0]


class TenantMix:
    """Weighted multi-tenant mix with Zipf-skewed prefix sharing.

    Each arrival draws a tenant proportional to ``weights`` and, with
    probability ``keyed_fraction``, a shared-prefix key from that
    tenant's pool of ``prefix_pool`` keys under a Zipf(``prefix_skew``)
    rank distribution — rank 1 is the hot system prompt everyone shares,
    the tail is long. The key value is deterministic
    (``"{tenant}-p{rank}"``) so affinity routing and the KV pool see the
    same hot keys across runs.
    """

    def __init__(self, weights: Dict[str, float], prefix_pool: int = 4,
                 prefix_skew: float = 1.1, keyed_fraction: float = 0.75):
        if not weights:
            weights = {"default": 1.0}
        self.weights = {str(t): float(w) for t, w in weights.items()}
        self._items = sorted(self.weights.items())
        self.keyed_fraction = min(max(float(keyed_fraction), 0.0), 1.0)
        n = max(int(prefix_pool), 1)
        ranks = [1.0 / (r ** float(prefix_skew)) for r in range(1, n + 1)]
        total = sum(ranks)
        cum, acc = [], 0.0
        for w in ranks:
            acc += w / total
            cum.append(acc)
        self._prefix_cum = cum

    def pick(self, rng: random.Random) -> Tuple[str, Optional[str]]:
        """Draw ``(tenant, prefix-or-None)`` for one arrival."""
        tenant = weighted_choice(rng, self._items)
        if rng.random() >= self.keyed_fraction:
            return tenant, None
        rank = bisect.bisect_left(self._prefix_cum, rng.random()) + 1
        rank = min(rank, len(self._prefix_cum))
        return tenant, f"{tenant}-p{rank}"
