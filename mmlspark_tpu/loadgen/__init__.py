"""Open-loop load generation: arrival processes, declarative scenarios,
chaos composition, and SLO scorecards the tuner can learn from.

The package exists because closed-loop benches (send → wait → send) hide
queueing collapse: a slow server throttles its own load generator, so
p99 stays flat while real users would be stacking up — coordinated
omission. Everything here measures latency from each request's
*scheduled* send instant instead, drives traffic through the same
admission/breaker/fault machinery production requests hit, and emits one
BENCH-style scorecard per scenario (mirrored to ``mmlspark_scenario_*``
metrics and harvested into the ``ObservationStore``).

* :mod:`.arrivals` — seeded Poisson/diurnal arrivals, heavy-tailed
  sizes, multi-tenant mix with Zipf prefix-sharing skew.
* :mod:`.scenarios` — :class:`~.scenarios.Scenario` registry, the
  open-loop runner, chaos scripts, closed-loop probe.
* :mod:`.scorecard` — scorecard assembly, fairness error, counter
  reconciliation, metric mirrors, ObservationStore harvest.
* :mod:`.sessions` — journal-backed long-decode session drill: worker
  kills mid-decode, recovery over the real ``/_adopt`` hop, token-parity
  accounting (``sessions_lost``/``sessions_recovered``/
  ``recovery_p99_ms`` in the scorecard).
* :mod:`.progress` — the live snapshot behind ``GET /debug/scenario``.
"""

from .arrivals import (Arrival, TenantMix, diurnal_offsets,
                       heavy_tail_rows, interarrivals, poisson_offsets,
                       weighted_choice)
from .progress import ScenarioProgress, get_progress, reset_progress
from .scenarios import (SCENARIOS, Scenario, closed_loop_probe,
                        cluster_echo_engine, get_scenario, plan,
                        run_scenario)
from .scorecard import (build_scorecard, counters_snapshot, fairness_error,
                        harvest_slo, merged_requests_total, quantiles_ms)
from .sessions import SessionDrill, session_token

__all__ = [
    "Arrival", "SCENARIOS", "Scenario", "ScenarioProgress", "SessionDrill",
    "TenantMix",
    "build_scorecard", "closed_loop_probe", "cluster_echo_engine",
    "counters_snapshot", "diurnal_offsets", "fairness_error",
    "get_progress", "get_scenario", "harvest_slo", "heavy_tail_rows",
    "interarrivals", "merged_requests_total", "plan", "poisson_offsets",
    "quantiles_ms", "reset_progress", "run_scenario", "session_token",
    "weighted_choice",
]
