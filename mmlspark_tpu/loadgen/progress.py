"""Live scenario progress: the process-global snapshot behind
``GET /debug/scenario``.

The scenario runner (``loadgen.scenarios``) updates this singleton as it
drives traffic; the serving plane's debug route reads it — on BOTH
WorkerServer transports — so an operator watching a chaos drill can see
sent/completed/shed counts move without waiting for the final scorecard.
Standalone on purpose: ``serving.server`` imports this lazily, and this
module imports nothing from ``serving``, so there is no cycle.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

__all__ = ["ScenarioProgress", "get_progress", "set_progress",
           "reset_progress"]


class ScenarioProgress:
    """Thread-safe counters for the scenario currently driving traffic."""

    def __init__(self):
        self._lock = threading.Lock()
        self._reset_locked()

    def _reset_locked(self) -> None:
        self.scenario: Optional[str] = None
        self.state = "idle"            # idle | running | done
        self.total = 0
        self.sent = 0
        self.done = 0
        self.ok = 0
        self.shed = 0
        self.errors = 0
        self.retries = 0
        self.started_t: Optional[float] = None
        self.updated_t: Optional[float] = None
        self.summary: Optional[Dict[str, object]] = None

    def begin(self, scenario: str, total: int) -> None:
        with self._lock:
            self._reset_locked()
            self.scenario = scenario
            self.state = "running"
            self.total = int(total)
            self.started_t = time.time()
            self.updated_t = self.started_t

    def note_sent(self, n: int = 1) -> None:
        with self._lock:
            self.sent += n
            self.updated_t = time.time()

    def note_done(self, outcome: str, retries: int = 0) -> None:
        with self._lock:
            self.done += 1
            self.retries += int(retries)
            if outcome == "ok":
                self.ok += 1
            elif outcome == "shed":
                self.shed += 1
            else:
                self.errors += 1
            self.updated_t = time.time()

    def finish(self, summary: Optional[Dict[str, object]] = None) -> None:
        with self._lock:
            self.state = "done"
            self.summary = dict(summary) if summary else None
            self.updated_t = time.time()

    def snapshot(self) -> Dict[str, object]:
        """JSON-safe live view (the /debug/scenario payload)."""
        with self._lock:
            out: Dict[str, object] = {
                "scenario": self.scenario, "state": self.state,
                "total": self.total, "sent": self.sent, "done": self.done,
                "ok": self.ok, "shed": self.shed, "errors": self.errors,
                "retries": self.retries,
                "started_t": self.started_t, "updated_t": self.updated_t,
            }
            if self.started_t is not None and self.state == "running":
                # a live debug-view field, not accumulated telemetry: the
                # run's durable numbers go through mmlspark_scenario_*
                # metrics in loadgen.scorecard
                # tpulint: disable=TPU007
                out["elapsed_s"] = round(time.time() - self.started_t, 3)
            if self.summary is not None:
                out["summary"] = dict(self.summary)
            return out


_progress_lock = threading.Lock()
_progress: Optional[ScenarioProgress] = None


def get_progress() -> ScenarioProgress:
    """The process-global progress object, created on first use."""
    global _progress
    with _progress_lock:
        if _progress is None:
            _progress = ScenarioProgress()
        return _progress


def set_progress(progress: ScenarioProgress) -> None:
    global _progress
    with _progress_lock:
        _progress = progress


def reset_progress() -> None:
    global _progress
    with _progress_lock:
        _progress = None
