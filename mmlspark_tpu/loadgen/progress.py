"""Live scenario progress: the process-global snapshot behind
``GET /debug/scenario``.

The scenario runner (``loadgen.scenarios``) updates this singleton as it
drives traffic; the serving plane's debug route reads it — on BOTH
WorkerServer transports — so an operator watching a chaos drill can see
sent/completed/shed counts move without waiting for the final scorecard.
Standalone on purpose: ``serving.server`` imports this lazily, and this
module imports nothing from ``serving``, so there is no cycle.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

__all__ = ["ScenarioProgress", "get_progress", "set_progress",
           "reset_progress"]

#: live-timeline ring size cap — the structure is preallocated at begin()
#: and never grows, whatever the scenario duration (overflow completions
#: clamp into the last bucket)
MAX_LIVE_BUCKETS = 64


class ScenarioProgress:
    """Thread-safe counters for the scenario currently driving traffic,
    plus a fixed-size live timeline (per-bucket outcome counts and
    latency stats) so ``GET /debug/scenario`` shows the run's shape
    *mid-run*, not just after the scorecard lands."""

    def __init__(self):
        self._lock = threading.Lock()
        self._reset_locked()

    def _reset_locked(self) -> None:
        self.scenario: Optional[str] = None
        self.state = "idle"            # idle | running | done
        self.total = 0
        self.sent = 0
        self.done = 0
        self.ok = 0
        self.shed = 0
        self.errors = 0
        self.retries = 0
        self.started_t: Optional[float] = None
        self.updated_t: Optional[float] = None
        self.summary: Optional[Dict[str, object]] = None
        self.bucket_s = 1.0
        # preallocated at begin(); [ok, shed, errors, lat_sum, lat_max, n]
        self._buckets: list = []
        self._hi_bucket = -1

    def begin(self, scenario: str, total: int,
              duration_s: Optional[float] = None,
              bucket_s: Optional[float] = None) -> None:
        with self._lock:
            self._reset_locked()
            self.scenario = scenario
            self.state = "running"
            self.total = int(total)
            self.started_t = time.time()
            self.updated_t = self.started_t
            if bucket_s is None:
                bucket_s = (max(round(float(duration_s) / 12.0, 3), 0.1)
                            if duration_s else 1.0)
            self.bucket_s = float(bucket_s)
            n = MAX_LIVE_BUCKETS
            if duration_s:
                # +2 slack: completions trail the planned duration
                n = min(n, int(float(duration_s) / self.bucket_s) + 2)
            self._buckets = [[0, 0, 0, 0.0, 0.0, 0] for _ in range(n)]

    def note_sent(self, n: int = 1) -> None:
        with self._lock:
            self.sent += n
            self.updated_t = time.time()

    def note_done(self, outcome: str, retries: int = 0,
                  at_s: Optional[float] = None,
                  lat_s: Optional[float] = None) -> None:
        with self._lock:
            self.done += 1
            self.retries += int(retries)
            if outcome == "ok":
                self.ok += 1
            elif outcome == "shed":
                self.shed += 1
            else:
                self.errors += 1
            self.updated_t = time.time()
            if at_s is not None and self._buckets:
                i = min(max(int(at_s // self.bucket_s), 0),
                        len(self._buckets) - 1)
                if i > self._hi_bucket:
                    self._hi_bucket = i
                b = self._buckets[i]
                col = {"ok": 0, "shed": 1}.get(outcome, 2)
                b[col] += 1
                if outcome == "ok" and lat_s is not None:
                    b[3] += float(lat_s)
                    if lat_s > b[4]:
                        b[4] = float(lat_s)
                    b[5] += 1

    def finish(self, summary: Optional[Dict[str, object]] = None) -> None:
        with self._lock:
            self.state = "done"
            self.summary = dict(summary) if summary else None
            self.updated_t = time.time()

    def snapshot(self) -> Dict[str, object]:
        """JSON-safe live view (the /debug/scenario payload)."""
        with self._lock:
            out: Dict[str, object] = {
                "scenario": self.scenario, "state": self.state,
                "total": self.total, "sent": self.sent, "done": self.done,
                "ok": self.ok, "shed": self.shed, "errors": self.errors,
                "retries": self.retries,
                "started_t": self.started_t, "updated_t": self.updated_t,
            }
            if self.started_t is not None and self.state == "running":
                # a live debug-view field, not accumulated telemetry: the
                # run's durable numbers go through mmlspark_scenario_*
                # metrics in loadgen.scorecard
                # tpulint: disable=TPU007
                out["elapsed_s"] = round(time.time() - self.started_t, 3)
            if self._hi_bucket >= 0:
                rows = []
                for i in range(self._hi_bucket + 1):
                    ok, shed, errors, lat_sum, lat_max, n = self._buckets[i]
                    rows.append({
                        "t0": round(i * self.bucket_s, 3),
                        "ok": ok, "shed": shed, "errors": errors,
                        "lat_mean_ms": (round(lat_sum / n * 1e3, 3)
                                        if n else None),
                        "lat_max_ms": (round(lat_max * 1e3, 3)
                                       if n else None)})
                out["timeline"] = {"bucket_s": self.bucket_s,
                                   "buckets": rows}
            if self.summary is not None:
                out["summary"] = dict(self.summary)
            return out


_progress_lock = threading.Lock()
_progress: Optional[ScenarioProgress] = None


def get_progress() -> ScenarioProgress:
    """The process-global progress object, created on first use."""
    global _progress
    with _progress_lock:
        if _progress is None:
            _progress = ScenarioProgress()
        return _progress


def set_progress(progress: ScenarioProgress) -> None:
    global _progress
    with _progress_lock:
        _progress = progress


def reset_progress() -> None:
    global _progress
    with _progress_lock:
        _progress = None
