"""Per-scenario SLO scorecards: BENCH-style JSON the tuner can learn from.

One scenario run produces one scorecard: goodput, coordinated-omission-
corrected latency quantiles (measured from each request's *scheduled*
send time — the open-loop number a closed-loop bench structurally cannot
see), shed rate, retry amplification, breaker flap count, DRR fairness
error against the configured tenant weights, and per-tenant cost joined
from the serving plane's ``/debug/costs`` payload. The same numbers are
mirrored to ``mmlspark_scenario_*`` metrics and harvested into the
``ObservationStore`` through the existing ``slo_scorecard`` source, so
``resolve_tuning`` sees traffic-shaped truth next to bench throughput.

Scorecard quantiles are ONE-SHOT batch statistics over a completed run's
sample list — not a rolling window (the serving plane's live windows stay
in ``observability.slo``); that is why this module computes them directly
instead of growing another tracker.
"""

from __future__ import annotations

import math
import time
from typing import Dict, List, Optional, Sequence

from ..observability import counter as _metric_counter
from ..observability import gauge as _metric_gauge
from ..observability import snapshot as _registry_snapshot
from ..tuning.observations import harvest_scorecard as _harvest_scorecard

__all__ = ["build_scorecard", "build_timeline", "counters_delta",
           "counters_snapshot", "fairness_error", "harvest_slo",
           "merged_requests_total", "quantiles_ms"]

_M_SCN_REQUESTS = _metric_counter(
    "mmlspark_scenario_requests_total",
    "Scenario-harness requests by final outcome (ok/shed/error/lost)",
    ("scenario", "outcome"))
_M_SCN_RETRIES = _metric_counter(
    "mmlspark_scenario_retries_total",
    "Scenario-harness retry sends (beyond each request's first attempt)",
    ("scenario",))
_M_SCN_GOODPUT = _metric_gauge(
    "mmlspark_scenario_goodput_rps",
    "Completed-OK request rate of the last run of each scenario",
    ("scenario",))
_M_SCN_P99 = _metric_gauge(
    "mmlspark_scenario_p99_ms",
    "Coordinated-omission-corrected open-loop p99 of the last run",
    ("scenario",))
_M_SCN_FAIRNESS = _metric_gauge(
    "mmlspark_scenario_fairness_error",
    "DRR fairness error (0 = per-tenant goodput shares match weights)",
    ("scenario",))
_M_SCN_SESSIONS = _metric_counter(
    "mmlspark_scenario_sessions_total",
    "Session-drill decode sessions by final outcome "
    "(completed/recovered/lost)", ("scenario", "outcome"))
_M_SCN_RECOVERY_P99 = _metric_gauge(
    "mmlspark_scenario_session_recovery_p99_ms",
    "p99 session failover latency (journal scan -> /_adopt accepted) of "
    "the last run", ("scenario",))


def _quantile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank quantile over an already-sorted sample list."""
    n = len(sorted_vals)
    k = int(round(q * (n - 1)))
    return float(sorted_vals[min(max(k, 0), n - 1)])


def quantiles_ms(latencies_s: Sequence[float]) -> Optional[Dict[str, float]]:
    """p50/p99/p999/max in milliseconds, None for an empty sample."""
    if not latencies_s:
        return None
    s = sorted(latencies_s)
    qs = {"p50_ms": 0.50, "p99_ms": 0.99, "p999_ms": 0.999}
    out = {name: round(_quantile(s, q) * 1e3, 3) for name, q in qs.items()}
    out["max_ms"] = round(s[-1] * 1e3, 3)
    out["n"] = len(s)
    return out


def fairness_error(goodput: Dict[str, float],
                   weights: Dict[str, float]) -> float:
    """Total-variation distance between achieved per-tenant goodput
    shares and the configured weight shares, over the tenants that sent
    traffic: 0.0 means DRR delivered exactly weight-proportional goodput,
    1.0 means one tenant got everything another was owed."""
    tenants = [t for t in weights if t in goodput]
    if not tenants:
        tenants = sorted(set(goodput) | set(weights))
    if not tenants:
        return 0.0
    g_total = sum(max(goodput.get(t, 0.0), 0.0) for t in tenants)
    w_total = sum(max(float(weights.get(t, 0.0)), 0.0) for t in tenants)
    if g_total <= 0 or w_total <= 0:
        return 0.0 if g_total == w_total else 1.0
    err = 0.0
    for t in tenants:
        g_share = max(goodput.get(t, 0.0), 0.0) / g_total
        w_share = max(float(weights.get(t, 0.0)), 0.0) / w_total
        err += abs(g_share - w_share)
    return round(err / 2.0, 6)


def build_timeline(samples: Sequence[Optional[dict]], *,
                   duration_s: float,
                   weights: Optional[Dict[str, float]] = None,
                   bucket_s: Optional[float] = None) -> Dict[str, object]:
    """Time-resolved scorecard sub-record: the run as fixed-width buckets.

    Each landed sample is assigned to the bucket of its *scheduled*
    arrival offset (``at``), so the timeline shows the offered-load shape
    the scenario planned (diurnal waves, bursts) with the outcomes that
    befell it — a mid-run worker restart reads as a goodput dip and a
    p99 spike in the buckets it hit, then recovery. Per bucket: arrival/
    ok/shed/error counts, goodput_rps, coordinated-omission-corrected
    p99 over scheduled-send latency, and the DRR fairness error of that
    bucket's goodput against the configured tenant ``weights``.
    """
    landed = [s for s in samples
              if s is not None and s.get("at") is not None]
    if bucket_s is None:
        # ~12 buckets per run, floored so sub-second runs still resolve
        bucket_s = max(round(float(duration_s) / 12.0, 3), 0.1)
    bucket_s = float(bucket_s)
    if not landed:
        return {"bucket_s": bucket_s, "buckets": []}
    count = int(math.floor(max(float(s["at"]) for s in landed)
                           / bucket_s)) + 1
    rows: List[dict] = [
        {"t0": round(i * bucket_s, 3), "arrivals": 0, "ok": 0,
         "shed": 0, "errors": 0} for i in range(count)]
    lats: List[List[float]] = [[] for _ in range(count)]
    tenant_ok: List[Dict[str, float]] = [{} for _ in range(count)]
    for s in landed:
        i = min(int(float(s["at"]) // bucket_s), count - 1)
        row = rows[i]
        row["arrivals"] += 1
        outcome = s.get("outcome")
        key = {"ok": "ok", "shed": "shed"}.get(outcome, "errors")
        row[key] += 1
        if outcome == "ok":
            tenant = str(s.get("tenant", "default"))
            tenant_ok[i][tenant] = tenant_ok[i].get(tenant, 0.0) + 1.0
            if s.get("sched_lat_s") is not None:
                lats[i].append(float(s["sched_lat_s"]))
    for i, row in enumerate(rows):
        row["goodput_rps"] = round(row["ok"] / bucket_s, 3)
        row["p99_ms"] = (round(_quantile(sorted(lats[i]), 0.99) * 1e3, 3)
                         if lats[i] else None)
        row["fairness_error"] = fairness_error(tenant_ok[i], weights or {})
    return {"bucket_s": bucket_s, "buckets": rows}


# -- counter snapshots (breaker flaps, sheds, faults) -------------------------

def _series_sum(snap: dict, name: str, **labels) -> float:
    metric = snap.get(name) or {}
    total = 0.0
    for s in metric.get("series", ()):  # type: ignore[union-attr]
        row = s.get("labels", {})
        if all(row.get(k) == v for k, v in labels.items()):
            total += float(s.get("value", 0.0))
    return total


def counters_snapshot() -> Dict[str, float]:
    """The cumulative counters the scorecard reports as run deltas:
    breaker transitions (total and into-open = flaps), shed totals, and
    injected-fault count. Take one before the run and one after."""
    snap = _registry_snapshot()
    return {
        "breaker_transitions": _series_sum(
            snap, "mmlspark_breaker_transitions_total"),
        "breaker_opens": _series_sum(
            snap, "mmlspark_breaker_transitions_total", to="open"),
        "requests_shed": _series_sum(snap, "mmlspark_requests_shed_total"),
        "wfq_shed": _series_sum(snap, "mmlspark_wfq_shed_total"),
        "faults_injected": _series_sum(snap,
                                       "mmlspark_faults_injected_total"),
        "serving_requests": _series_sum(snap,
                                        "mmlspark_serving_requests_total"),
    }


def counters_delta(before: Dict[str, float],
                   after: Dict[str, float]) -> Dict[str, float]:
    return {k: round(after.get(k, 0.0) - before.get(k, 0.0), 6)
            for k in after}


def merged_requests_total(prom_text: str) -> float:
    """Sum every ``mmlspark_serving_requests_total`` series in a
    Prometheus exposition (the driver's federated ``/debug/cluster``
    ``metrics`` field) — the cluster-merged request counter the scorecard
    reconciles against."""
    total = 0.0
    for line in prom_text.splitlines():
        if line.startswith("mmlspark_serving_requests_total{"):
            total += float(line.rsplit(" ", 1)[1])
    return total


# -- the scorecard ------------------------------------------------------------

def build_scorecard(scenario, samples: List[dict], *,
                    window_s: float,
                    counters_before: Optional[Dict[str, float]] = None,
                    counters_after: Optional[Dict[str, float]] = None,
                    costs: Optional[dict] = None,
                    cluster_view: Optional[dict] = None,
                    closed_loop: Optional[dict] = None,
                    mesh_shape: Optional[str] = None,
                    kv_dtype: Optional[str] = None,
                    sessions: Optional[dict] = None) -> dict:
    """Assemble the per-scenario scorecard and mirror it to metrics.

    ``samples`` is the runner's per-arrival outcome list (one dict per
    planned arrival — a missing entry is a LOST request and the headline
    failure); ``costs`` is the raw ``/debug/costs`` JSON payload;
    ``cluster_view`` carries the federated reconciliation block the
    runner fills from ``/debug/cluster``.
    """
    arrivals = len(samples)
    landed = [s for s in samples if s is not None]
    by_outcome = {"ok": 0, "shed": 0, "error": 0}
    attempts = retries = honored = 0
    sched_lats: List[float] = []
    send_lats: List[float] = []
    send_lags: List[float] = []
    for s in landed:
        by_outcome[s.get("outcome", "error")] = \
            by_outcome.get(s.get("outcome", "error"), 0) + 1
        attempts += int(s.get("attempts", 1))
        retries += max(int(s.get("attempts", 1)) - 1, 0)
        honored += int(s.get("honored_retries", 0))
        lag = s.get("send_lag_s")
        if lag is not None:
            send_lags.append(float(lag))
        if s.get("outcome") == "ok":
            if s.get("sched_lat_s") is not None:
                sched_lats.append(float(s["sched_lat_s"]))
            if s.get("send_lat_s") is not None:
                send_lats.append(float(s["send_lat_s"]))
    lost = arrivals - len(landed)
    ok = by_outcome.get("ok", 0)
    window_s = max(float(window_s), 1e-9)

    weights = dict(getattr(scenario, "tenants", None) or {})
    tenant_rows: Dict[str, dict] = {}
    goodput_by_tenant: Dict[str, float] = {}
    for s in landed:
        t = str(s.get("tenant", "default"))
        row = tenant_rows.setdefault(
            t, {"weight": float(weights.get(t, 1.0)), "arrivals": 0,
                "ok": 0, "shed": 0, "errors": 0})
        row["arrivals"] += 1
        key = {"ok": "ok", "shed": "shed"}.get(s.get("outcome"), "errors")
        row[key] += 1
    for t, row in tenant_rows.items():
        row["goodput_rps"] = round(row["ok"] / window_s, 3)
        goodput_by_tenant[t] = float(row["ok"])
    total_ok = sum(goodput_by_tenant.values())
    for t, row in tenant_rows.items():
        row["goodput_share"] = (round(row["ok"] / total_ok, 4)
                                if total_ok else 0.0)

    # join cost-per-request by tenant from the /debug/costs payload: the
    # weighted scalar cost of that tenant's api-route classes over its
    # completed requests
    if costs:
        cost_by_tenant: Dict[str, float] = {}
        for cls in costs.get("classes", ()):
            if cls.get("route") not in (None, "api"):
                continue
            t = str(cls.get("tenant", "default"))
            cost_by_tenant[t] = cost_by_tenant.get(t, 0.0) \
                + float(cls.get("weighted_cost", 0.0))
        for t, row in tenant_rows.items():
            spent = cost_by_tenant.get(t)
            row["weighted_cost"] = (round(spent, 9)
                                    if spent is not None else None)
            row["cost_per_request"] = (
                round(spent / row["ok"], 9)
                if spent is not None and row["ok"] else None)

    fair_err = fairness_error(goodput_by_tenant, weights)
    deltas = (counters_delta(counters_before, counters_after)
              if counters_before is not None and counters_after is not None
              else {})

    card: Dict[str, object] = {
        "scenario": getattr(scenario, "name", "?"),
        "seed": getattr(scenario, "seed", None),
        "loop_mode": "open",
        "t": time.time(),
        "duration_s": getattr(scenario, "duration_s", None),
        "window_s": round(window_s, 3),
        "mesh_shape": mesh_shape,
        "kv_dtype": kv_dtype,
        "arrivals": arrivals,
        "ok": ok,
        "shed": by_outcome.get("shed", 0),
        "errors": by_outcome.get("error", 0),
        "lost": lost,
        "goodput_rps": round(ok / window_s, 3),
        "shed_rate": round(by_outcome.get("shed", 0) / arrivals, 4)
        if arrivals else 0.0,
        # coordinated-omission-corrected: measured from each request's
        # SCHEDULED send instant, so time spent queued behind a saturated
        # server (or a backed-up sender) counts against the server
        "latency_ms": quantiles_ms(sched_lats),
        # from the actual send instant — the closed-loop-comparable view
        "service_latency_ms": quantiles_ms(send_lats),
        "send_lag_ms": quantiles_ms(send_lags),
        "client_saturated": bool(
            send_lags and _quantile(sorted(send_lags), 0.99) > 0.25),
        "retry": {
            "attempts_total": attempts,
            "retries": retries,
            "honored_retry_after": honored,
            # sends per planned arrival: 1.0 = no retries at all
            "amplification": round(attempts / arrivals, 4)
            if arrivals else 0.0,
        },
        "breaker": {
            "transitions": deltas.get("breaker_transitions"),
            "flaps": deltas.get("breaker_opens"),
        },
        "shed_counters": {
            "requests_shed": deltas.get("requests_shed"),
            "wfq_shed": deltas.get("wfq_shed"),
        },
        "faults_injected": deltas.get("faults_injected"),
        "tenants": tenant_rows,
        "fairness_error": fair_err,
        # time-resolved view of the same run (see build_timeline): the
        # scenario's load shape and how each slice of it fared
        "timeline": build_timeline(
            samples,
            duration_s=float(getattr(scenario, "duration_s", 0.0)
                             or window_s),
            weights=weights),
        "cluster": dict(cluster_view) if cluster_view else None,
        "closed_loop": dict(closed_loop) if closed_loop else None,
        # session-drill block (loadgen.sessions): decode sessions that
        # rode the run, how many survived the chaos script, and the
        # failover latency tail — sessions_lost == 0 is the CI gate
        "sessions": dict(sessions) if sessions else None,
    }

    name = str(card["scenario"])
    for outcome, n in (("ok", ok), ("shed", by_outcome.get("shed", 0)),
                       ("error", by_outcome.get("error", 0)),
                       ("lost", lost)):
        if n:
            _M_SCN_REQUESTS.inc(n, scenario=name, outcome=outcome)
    if retries:
        _M_SCN_RETRIES.inc(retries, scenario=name)
    _M_SCN_GOODPUT.set(float(card["goodput_rps"]), scenario=name)
    lat = card["latency_ms"]
    if isinstance(lat, dict):
        _M_SCN_P99.set(float(lat["p99_ms"]), scenario=name)
    _M_SCN_FAIRNESS.set(fair_err, scenario=name)
    if sessions:
        n_lost = int(sessions.get("lost", 0))
        n_rec = int(sessions.get("recovered", 0))
        n_done = int(sessions.get("sessions", 0)) - n_lost - n_rec
        for outcome, n in (("completed", max(n_done, 0)),
                           ("recovered", n_rec), ("lost", n_lost)):
            if n:
                _M_SCN_SESSIONS.inc(n, scenario=name, outcome=outcome)
        if sessions.get("recovery_p99_ms") is not None:
            _M_SCN_RECOVERY_P99.set(float(sessions["recovery_p99_ms"]),
                                    scenario=name)
    return card


def harvest_slo(slo_scorecard: dict, store=None,
                placement: str = "scenario") -> int:
    """Land the run's SLO scorecard (``SloTracker.scorecard()`` — the
    same tracker the serving plane observed this scenario's traffic into)
    in the ObservationStore under the existing ``source="slo_scorecard"``
    rows, so the tuner's cost model reads traffic-shaped truth through
    the exact schema it already joins. (Cost rows land server-side: the
    runner's ``/debug/costs`` fetch harvests ``source="cost_ledger"``
    rows in the serving process.)"""
    return _harvest_scorecard(slo_scorecard, store=store,
                              placement=placement)
