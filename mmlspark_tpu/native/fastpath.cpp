/* Native host-side fast paths.
 *
 * Role of the reference's native runtime layer (SURVEY.md L0/L1): the
 * reference reaches C++ for everything between the JVM and the accelerator —
 * LightGBM's ChunkedArray marshalling (dataset/DatasetAggregator.scala),
 * VW's murmur hashing (VowpalWabbitMurmurWithPrefix.scala), ONNX tensor
 * creation (ONNXModel.scala:357-402, "the throughput killer").  Here the
 * device math belongs to XLA, but the host-side marshalling before
 * jax.device_put is pure Python loops — these are their C++ replacements:
 *
 *   murmur3        — single feature-name hash (VW featurizer)
 *   murmur3_batch  — hash a sequence of byte-strings in one call
 *   pad_sparse     — (indices, values) object rows -> padded [n,K] buffers
 *   stack_rows     — object column of float vectors -> dense (n,d) float32
 *
 * Exposed through mmlspark_tpu/native/__init__.py with pure-Python
 * fallbacks, so the package works without a compiler.
 */

#define PY_SSIZE_T_CLEAN
#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#include <Python.h>
#include <numpy/arrayobject.h>

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

static uint32_t murmur3_32(const uint8_t* data, size_t len, uint32_t seed) {
  const uint32_t c1 = 0xcc9e2d51u, c2 = 0x1b873593u;
  uint32_t h = seed;
  const size_t nblocks = len / 4;
  for (size_t i = 0; i < nblocks; i++) {
    uint32_t k;
    std::memcpy(&k, data + 4 * i, 4);
    k *= c1; k = rotl32(k, 15); k *= c2;
    h ^= k; h = rotl32(h, 13); h = h * 5 + 0xe6546b64u;
  }
  const uint8_t* tail = data + nblocks * 4;
  uint32_t k1 = 0;
  switch (len & 3) {
    case 3: k1 ^= (uint32_t)tail[2] << 16; [[fallthrough]];
    case 2: k1 ^= (uint32_t)tail[1] << 8;  [[fallthrough]];
    case 1: k1 ^= (uint32_t)tail[0];
            k1 *= c1; k1 = rotl32(k1, 15); k1 *= c2; h ^= k1;
  }
  h ^= (uint32_t)len;
  h ^= h >> 16; h *= 0x85ebca6bu; h ^= h >> 13; h *= 0xc2b2ae35u; h ^= h >> 16;
  return h;
}

static PyObject* py_murmur3(PyObject*, PyObject* args) {
  Py_buffer buf;
  unsigned int seed;
  if (!PyArg_ParseTuple(args, "y*I", &buf, &seed)) return nullptr;
  uint32_t h = murmur3_32((const uint8_t*)buf.buf, (size_t)buf.len, seed);
  PyBuffer_Release(&buf);
  return PyLong_FromUnsignedLong(h);
}

/* murmur3_batch(seq_of_bytes, seed, mask) -> uint32[n] */
static PyObject* py_murmur3_batch(PyObject*, PyObject* args) {
  PyObject* seq;
  unsigned int seed, mask;
  if (!PyArg_ParseTuple(args, "OII", &seq, &seed, &mask)) return nullptr;
  PyObject* fast = PySequence_Fast(seq, "murmur3_batch expects a sequence");
  if (!fast) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  npy_intp dims[1] = {n};
  PyArrayObject* out =
      (PyArrayObject*)PyArray_SimpleNew(1, dims, NPY_UINT32);
  if (!out) { Py_DECREF(fast); return nullptr; }
  uint32_t* o = (uint32_t*)PyArray_DATA(out);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* it = PySequence_Fast_GET_ITEM(fast, i);
    char* p; Py_ssize_t len;
    if (PyBytes_AsStringAndSize(it, &p, &len) < 0) {
      Py_DECREF(fast); Py_DECREF(out); return nullptr;
    }
    o[i] = murmur3_32((const uint8_t*)p, (size_t)len, seed) & mask;
  }
  Py_DECREF(fast);
  return (PyObject*)out;
}

/* pad_sparse(rows, K) -> (int32[n,K], float32[n,K])
 * rows: sequence of (indices, values) array pairs. */
static PyObject* py_pad_sparse(PyObject*, PyObject* args) {
  PyObject* seq;
  int K;
  if (!PyArg_ParseTuple(args, "Oi", &seq, &K)) return nullptr;
  PyObject* fast = PySequence_Fast(seq, "pad_sparse expects a sequence");
  if (!fast) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  npy_intp dims[2] = {n, K};
  PyArrayObject* idx = (PyArrayObject*)PyArray_ZEROS(2, dims, NPY_INT32, 0);
  PyArrayObject* val = (PyArrayObject*)PyArray_ZEROS(2, dims, NPY_FLOAT32, 0);
  if (!idx || !val) {
    Py_XDECREF(idx); Py_XDECREF(val); Py_DECREF(fast); return nullptr;
  }
  int32_t* ip = (int32_t*)PyArray_DATA(idx);
  float* vp = (float*)PyArray_DATA(val);
  for (Py_ssize_t r = 0; r < n; r++) {
    PyObject* pair = PySequence_Fast_GET_ITEM(fast, r);
    PyObject* pi = PySequence_GetItem(pair, 0);
    PyObject* pv = PySequence_GetItem(pair, 1);
    if (!pi || !pv) { Py_XDECREF(pi); Py_XDECREF(pv); goto fail; }
    {
      PyArrayObject* ai = (PyArrayObject*)PyArray_FROM_OTF(
          pi, NPY_INT64, NPY_ARRAY_IN_ARRAY | NPY_ARRAY_FORCECAST);
      PyArrayObject* av = (PyArrayObject*)PyArray_FROM_OTF(
          pv, NPY_FLOAT32, NPY_ARRAY_IN_ARRAY | NPY_ARRAY_FORCECAST);
      Py_DECREF(pi); Py_DECREF(pv);
      if (!ai || !av) { Py_XDECREF(ai); Py_XDECREF(av); goto fail; }
      Py_ssize_t k = PyArray_SIZE(ai);
      Py_ssize_t kv = PyArray_SIZE(av);
      if (kv < k) k = kv;   /* malformed row: clamp, never read past values */
      if (k > K) k = K;
      const int64_t* si = (const int64_t*)PyArray_DATA(ai);
      const float* sv = (const float*)PyArray_DATA(av);
      for (Py_ssize_t j = 0; j < k; j++) {
        ip[r * K + j] = (int32_t)si[j];
        vp[r * K + j] = sv[j];
      }
      Py_DECREF(ai); Py_DECREF(av);
    }
  }
  Py_DECREF(fast);
  return Py_BuildValue("(NN)", idx, val);
fail:
  Py_DECREF(fast); Py_DECREF(idx); Py_DECREF(val);
  return nullptr;
}

/* stack_rows(seq_of_float_vectors, d) -> float32[n, d] (pad/truncate to d) */
static PyObject* py_stack_rows(PyObject*, PyObject* args) {
  PyObject* seq;
  int d;
  if (!PyArg_ParseTuple(args, "Oi", &seq, &d)) return nullptr;
  PyObject* fast = PySequence_Fast(seq, "stack_rows expects a sequence");
  if (!fast) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  npy_intp dims[2] = {n, d};
  PyArrayObject* out = (PyArrayObject*)PyArray_ZEROS(2, dims, NPY_FLOAT32, 0);
  if (!out) { Py_DECREF(fast); return nullptr; }
  float* op = (float*)PyArray_DATA(out);
  for (Py_ssize_t r = 0; r < n; r++) {
    PyObject* item = PySequence_Fast_GET_ITEM(fast, r);
    PyArrayObject* a = (PyArrayObject*)PyArray_FROM_OTF(
        item, NPY_FLOAT32, NPY_ARRAY_IN_ARRAY | NPY_ARRAY_FORCECAST);
    if (!a) { Py_DECREF(fast); Py_DECREF(out); return nullptr; }
    Py_ssize_t k = PyArray_SIZE(a);
    if (k > d) k = d;
    std::memcpy(op + (size_t)r * d, PyArray_DATA(a), (size_t)k * sizeof(float));
    Py_DECREF(a);
  }
  Py_DECREF(fast);
  return (PyObject*)out;
}

/* parse_libsvm(data: bytes) ->
 *   (float64 labels[n], int64 qids[n], int64 indptr[n+1],
 *    int32 indices[nnz], float32 values[nnz])
 * LightGBM's text format: "label [qid:Q] idx:val idx:val ... [# comment]".
 * qid is -1 for rows without one. The input MUST be a bytes object (its
 * buffer is NUL-terminated, which strtod/strtol parsing relies on). */
static PyObject* py_parse_libsvm(PyObject*, PyObject* args) {
  PyObject* bytes_obj;
  if (!PyArg_ParseTuple(args, "S", &bytes_obj)) return nullptr;
  const char* s = PyBytes_AS_STRING(bytes_obj);
  const char* end = s + PyBytes_GET_SIZE(bytes_obj);

  std::vector<double> labels;
  std::vector<int64_t> qids;
  std::vector<int64_t> indptr;
  std::vector<int32_t> indices;
  std::vector<float> values;
  indptr.push_back(0);

  const char* p = s;
  while (p < end) {
    const char* eol = (const char*)memchr(p, '\n', (size_t)(end - p));
    if (!eol) eol = end;
    const char* hash = (const char*)memchr(p, '#', (size_t)(eol - p));
    const char* lend = hash ? hash : eol;
    const char* q = p;
    while (q < lend && (*q == ' ' || *q == '\t' || *q == '\r')) q++;
    if (q >= lend) { p = eol + 1; continue; }  /* blank / comment-only */

    char* next;
    /* PyOS_string_to_double is locale-independent (strtod reads ',' as the
     * decimal point under e.g. de_DE, diverging from the Python fallback) */
    double lab = PyOS_string_to_double(q, &next, nullptr);
    if (PyErr_Occurred()) PyErr_Clear();
    if (next == q || next > lend) {
      PyErr_Format(PyExc_ValueError, "libsvm: bad label at byte %zd",
                   (Py_ssize_t)(q - s));
      return nullptr;
    }
    q = next;
    int64_t qid = -1;
    while (q < lend) {
      while (q < lend && (*q == ' ' || *q == '\t' || *q == '\r')) q++;
      if (q >= lend) break;
      if (lend - q >= 4 && memcmp(q, "qid:", 4) == 0) {
        q += 4;
        qid = (int64_t)strtoll(q, &next, 10);
        if (next == q || next > lend) {  /* bound: strtoll would skip '\n'
                                          * and eat the NEXT line's label */
          PyErr_Format(PyExc_ValueError, "libsvm: bad qid at byte %zd",
                       (Py_ssize_t)(q - s));
          return nullptr;
        }
        q = next;
        continue;
      }
      long long idx = strtoll(q, &next, 10);
      if (next == q || next >= lend || *next != ':') {
        PyErr_Format(PyExc_ValueError,
                     "libsvm: bad feature token at byte %zd",
                     (Py_ssize_t)(q - s));
        return nullptr;
      }
      if (idx < 0 || idx > 0x7fffffffLL) {
        /* an unchecked (int32_t) cast would silently wrap 2^32+1 -> 1 and
         * write the value into the wrong feature */
        PyErr_Format(PyExc_ValueError,
                     "libsvm: feature index %lld out of int32 range at "
                     "byte %zd", idx, (Py_ssize_t)(q - s));
        return nullptr;
      }
      q = next + 1;
      double v = PyOS_string_to_double(q, &next, nullptr);
      if (PyErr_Occurred()) PyErr_Clear();
      if (next == q) {
        PyErr_Format(PyExc_ValueError, "libsvm: bad value at byte %zd",
                     (Py_ssize_t)(q - s));
        return nullptr;
      }
      q = next;
      indices.push_back((int32_t)idx);
      values.push_back((float)v);
    }
    labels.push_back(lab);
    qids.push_back(qid);
    indptr.push_back((int64_t)indices.size());
    p = eol + 1;
  }

  npy_intp n = (npy_intp)labels.size();
  npy_intp np1 = n + 1;
  npy_intp nnz = (npy_intp)indices.size();
  PyArrayObject* a_lab = (PyArrayObject*)PyArray_SimpleNew(1, &n, NPY_FLOAT64);
  PyArrayObject* a_qid = (PyArrayObject*)PyArray_SimpleNew(1, &n, NPY_INT64);
  PyArrayObject* a_ptr = (PyArrayObject*)PyArray_SimpleNew(1, &np1, NPY_INT64);
  PyArrayObject* a_idx = (PyArrayObject*)PyArray_SimpleNew(1, &nnz, NPY_INT32);
  PyArrayObject* a_val = (PyArrayObject*)PyArray_SimpleNew(1, &nnz, NPY_FLOAT32);
  if (!a_lab || !a_qid || !a_ptr || !a_idx || !a_val) {
    Py_XDECREF(a_lab); Py_XDECREF(a_qid); Py_XDECREF(a_ptr);
    Py_XDECREF(a_idx); Py_XDECREF(a_val);
    return nullptr;
  }
  if (n) {
    std::memcpy(PyArray_DATA(a_lab), labels.data(), (size_t)n * 8);
    std::memcpy(PyArray_DATA(a_qid), qids.data(), (size_t)n * 8);
  }
  std::memcpy(PyArray_DATA(a_ptr), indptr.data(), (size_t)np1 * 8);
  if (nnz) {
    std::memcpy(PyArray_DATA(a_idx), indices.data(), (size_t)nnz * 4);
    std::memcpy(PyArray_DATA(a_val), values.data(), (size_t)nnz * 4);
  }
  return Py_BuildValue("(NNNNN)", a_lab, a_qid, a_ptr, a_idx, a_val);
}

static PyMethodDef Methods[] = {
    {"murmur3", py_murmur3, METH_VARARGS, "murmur3(data: bytes, seed) -> uint32"},
    {"murmur3_batch", py_murmur3_batch, METH_VARARGS,
     "murmur3_batch(seq_of_bytes, seed, mask) -> uint32[n]"},
    {"pad_sparse", py_pad_sparse, METH_VARARGS,
     "pad_sparse(rows, K) -> (int32[n,K], float32[n,K])"},
    {"stack_rows", py_stack_rows, METH_VARARGS,
     "stack_rows(seq, d) -> float32[n,d]"},
    {"parse_libsvm", py_parse_libsvm, METH_VARARGS,
     "parse_libsvm(data: bytes) -> (labels, qids, indptr, indices, values)"},
    {nullptr, nullptr, 0, nullptr}};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_fastpath", nullptr, -1, Methods,
    nullptr, nullptr, nullptr, nullptr};

PyMODINIT_FUNC PyInit__fastpath(void) {
  import_array();
  return PyModule_Create(&moduledef);
}
