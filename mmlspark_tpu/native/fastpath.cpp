/* Native host-side fast paths.
 *
 * Role of the reference's native runtime layer (SURVEY.md L0/L1): the
 * reference reaches C++ for everything between the JVM and the accelerator —
 * LightGBM's ChunkedArray marshalling (dataset/DatasetAggregator.scala),
 * VW's murmur hashing (VowpalWabbitMurmurWithPrefix.scala), ONNX tensor
 * creation (ONNXModel.scala:357-402, "the throughput killer").  Here the
 * device math belongs to XLA, but the host-side marshalling before
 * jax.device_put is pure Python loops — these are their C++ replacements:
 *
 *   murmur3        — single feature-name hash (VW featurizer)
 *   murmur3_batch  — hash a sequence of byte-strings in one call
 *   pad_sparse     — (indices, values) object rows -> padded [n,K] buffers
 *   stack_rows     — object column of float vectors -> dense (n,d) float32
 *
 * Exposed through mmlspark_tpu/native/__init__.py with pure-Python
 * fallbacks, so the package works without a compiler.
 */

#define PY_SSIZE_T_CLEAN
#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#include <Python.h>
#include <numpy/arrayobject.h>

#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

static uint32_t murmur3_32(const uint8_t* data, size_t len, uint32_t seed) {
  const uint32_t c1 = 0xcc9e2d51u, c2 = 0x1b873593u;
  uint32_t h = seed;
  const size_t nblocks = len / 4;
  for (size_t i = 0; i < nblocks; i++) {
    uint32_t k;
    std::memcpy(&k, data + 4 * i, 4);
    k *= c1; k = rotl32(k, 15); k *= c2;
    h ^= k; h = rotl32(h, 13); h = h * 5 + 0xe6546b64u;
  }
  const uint8_t* tail = data + nblocks * 4;
  uint32_t k1 = 0;
  switch (len & 3) {
    case 3: k1 ^= (uint32_t)tail[2] << 16; [[fallthrough]];
    case 2: k1 ^= (uint32_t)tail[1] << 8;  [[fallthrough]];
    case 1: k1 ^= (uint32_t)tail[0];
            k1 *= c1; k1 = rotl32(k1, 15); k1 *= c2; h ^= k1;
  }
  h ^= (uint32_t)len;
  h ^= h >> 16; h *= 0x85ebca6bu; h ^= h >> 13; h *= 0xc2b2ae35u; h ^= h >> 16;
  return h;
}

static PyObject* py_murmur3(PyObject*, PyObject* args) {
  Py_buffer buf;
  unsigned int seed;
  if (!PyArg_ParseTuple(args, "y*I", &buf, &seed)) return nullptr;
  uint32_t h = murmur3_32((const uint8_t*)buf.buf, (size_t)buf.len, seed);
  PyBuffer_Release(&buf);
  return PyLong_FromUnsignedLong(h);
}

/* murmur3_batch(seq_of_bytes, seed, mask) -> uint32[n] */
static PyObject* py_murmur3_batch(PyObject*, PyObject* args) {
  PyObject* seq;
  unsigned int seed, mask;
  if (!PyArg_ParseTuple(args, "OII", &seq, &seed, &mask)) return nullptr;
  PyObject* fast = PySequence_Fast(seq, "murmur3_batch expects a sequence");
  if (!fast) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  npy_intp dims[1] = {n};
  PyArrayObject* out =
      (PyArrayObject*)PyArray_SimpleNew(1, dims, NPY_UINT32);
  if (!out) { Py_DECREF(fast); return nullptr; }
  uint32_t* o = (uint32_t*)PyArray_DATA(out);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* it = PySequence_Fast_GET_ITEM(fast, i);
    char* p; Py_ssize_t len;
    if (PyBytes_AsStringAndSize(it, &p, &len) < 0) {
      Py_DECREF(fast); Py_DECREF(out); return nullptr;
    }
    o[i] = murmur3_32((const uint8_t*)p, (size_t)len, seed) & mask;
  }
  Py_DECREF(fast);
  return (PyObject*)out;
}

/* pad_sparse(rows, K) -> (int32[n,K], float32[n,K])
 * rows: sequence of (indices, values) array pairs. */
static PyObject* py_pad_sparse(PyObject*, PyObject* args) {
  PyObject* seq;
  int K;
  if (!PyArg_ParseTuple(args, "Oi", &seq, &K)) return nullptr;
  PyObject* fast = PySequence_Fast(seq, "pad_sparse expects a sequence");
  if (!fast) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  npy_intp dims[2] = {n, K};
  PyArrayObject* idx = (PyArrayObject*)PyArray_ZEROS(2, dims, NPY_INT32, 0);
  PyArrayObject* val = (PyArrayObject*)PyArray_ZEROS(2, dims, NPY_FLOAT32, 0);
  if (!idx || !val) {
    Py_XDECREF(idx); Py_XDECREF(val); Py_DECREF(fast); return nullptr;
  }
  int32_t* ip = (int32_t*)PyArray_DATA(idx);
  float* vp = (float*)PyArray_DATA(val);
  for (Py_ssize_t r = 0; r < n; r++) {
    PyObject* pair = PySequence_Fast_GET_ITEM(fast, r);
    PyObject* pi = PySequence_GetItem(pair, 0);
    PyObject* pv = PySequence_GetItem(pair, 1);
    if (!pi || !pv) { Py_XDECREF(pi); Py_XDECREF(pv); goto fail; }
    {
      PyArrayObject* ai = (PyArrayObject*)PyArray_FROM_OTF(
          pi, NPY_INT64, NPY_ARRAY_IN_ARRAY | NPY_ARRAY_FORCECAST);
      PyArrayObject* av = (PyArrayObject*)PyArray_FROM_OTF(
          pv, NPY_FLOAT32, NPY_ARRAY_IN_ARRAY | NPY_ARRAY_FORCECAST);
      Py_DECREF(pi); Py_DECREF(pv);
      if (!ai || !av) { Py_XDECREF(ai); Py_XDECREF(av); goto fail; }
      Py_ssize_t k = PyArray_SIZE(ai);
      Py_ssize_t kv = PyArray_SIZE(av);
      if (kv < k) k = kv;   /* malformed row: clamp, never read past values */
      if (k > K) k = K;
      const int64_t* si = (const int64_t*)PyArray_DATA(ai);
      const float* sv = (const float*)PyArray_DATA(av);
      for (Py_ssize_t j = 0; j < k; j++) {
        ip[r * K + j] = (int32_t)si[j];
        vp[r * K + j] = sv[j];
      }
      Py_DECREF(ai); Py_DECREF(av);
    }
  }
  Py_DECREF(fast);
  return Py_BuildValue("(NN)", idx, val);
fail:
  Py_DECREF(fast); Py_DECREF(idx); Py_DECREF(val);
  return nullptr;
}

/* stack_rows(seq_of_float_vectors, d) -> float32[n, d] (pad/truncate to d) */
static PyObject* py_stack_rows(PyObject*, PyObject* args) {
  PyObject* seq;
  int d;
  if (!PyArg_ParseTuple(args, "Oi", &seq, &d)) return nullptr;
  PyObject* fast = PySequence_Fast(seq, "stack_rows expects a sequence");
  if (!fast) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  npy_intp dims[2] = {n, d};
  PyArrayObject* out = (PyArrayObject*)PyArray_ZEROS(2, dims, NPY_FLOAT32, 0);
  if (!out) { Py_DECREF(fast); return nullptr; }
  float* op = (float*)PyArray_DATA(out);
  for (Py_ssize_t r = 0; r < n; r++) {
    PyObject* item = PySequence_Fast_GET_ITEM(fast, r);
    PyArrayObject* a = (PyArrayObject*)PyArray_FROM_OTF(
        item, NPY_FLOAT32, NPY_ARRAY_IN_ARRAY | NPY_ARRAY_FORCECAST);
    if (!a) { Py_DECREF(fast); Py_DECREF(out); return nullptr; }
    Py_ssize_t k = PyArray_SIZE(a);
    if (k > d) k = d;
    std::memcpy(op + (size_t)r * d, PyArray_DATA(a), (size_t)k * sizeof(float));
    Py_DECREF(a);
  }
  Py_DECREF(fast);
  return (PyObject*)out;
}

/* bin_columns(X[n,F] f32|f64, bounds[F,L] f64, lengths[F] i64, u16: int)
 *   -> uint8[n,F] | uint16[n,F]
 * Per-element quantile binning: out = searchsorted(bounds_j, x, "left") + 1
 * with NaN -> 0 (the missing bin). This is the dataset-construction hot
 * loop LightGBM runs in native code (LGBM_DatasetCreateFromMat,
 * dataset/DatasetAggregator.scala:331-356) — numpy's per-element
 * searchsorted costs ~70 ns on this host; the tight branch-light loop
 * below runs ~4-5x faster, which at HIGGS-11M is tens of seconds off the
 * training wall clock. */
/* branchless lower_bound (cmov per halving step, no mispredicts): index of
 * the first bound >= v == count of bounds < v. */
static inline int64_t lb_branchless(const double* a, int64_t n, double v) {
  if (n <= 0) return 0;
  const double* base = a;
  while (n > 1) {
    int64_t half = n >> 1;
    base = (base[half - 1] < v) ? base + half : base;
    n -= half;
  }
  return (base - a) + (*base < v);
}

/* Interpolation LUT over one feature's bounds: quantile bounds spread the
 * data ~uniformly, so a uniform-in-value bucket table narrows the search
 * range to O(1) bounds for almost every element, replacing the 8-step
 * dependent-load binary search with one LUT load and a 1-2 step search.
 * lut[i] = count of bounds < edge_i; for v in bucket i the answer lies in
 * [lut[i], lut[i+1]], widened by one bucket each side to absorb fp
 * rounding in the bucket computation. */
struct BinLut {
  static const int kBuckets = 1024;
  uint16_t lut[kBuckets + 1];
  double lo, scale;
  bool usable;

  void build(const double* b, int64_t lj) {
    usable = false;
    if (lj < 4 || lj > 65000) return;
    /* last bound is +inf by construction; interpolate over finite range */
    double fin_hi = b[lj - 2];
    if (!std::isfinite(b[0]) || !std::isfinite(fin_hi) || !(fin_hi > b[0]))
      return;
    lo = b[0];
    scale = (double)kBuckets / (fin_hi - lo);
    if (!std::isfinite(scale) || scale <= 0) return;
    for (int i = 0; i < kBuckets; i++) {
      double edge = lo + (double)i / scale;
      lut[i] = (uint16_t)lb_branchless(b, lj, edge);
    }
    /* values at/above the last finite bound must still find the top bins
     * (incl. the +inf cap), so the final range end is lj, not a count */
    lut[kBuckets] = (uint16_t)lj;
    usable = true;
  }

  inline int64_t find(const double* b, int64_t lj, double v) const {
    /* clamp in double space BEFORE the cast: (int64_t)inf is UB (x86
     * yields INT64_MIN, sending +inf values to bucket 0) */
    double t = (v - lo) * scale;
    int64_t bk;
    if (!(t > 0.0)) bk = 0;
    else if (t >= (double)(kBuckets - 1)) bk = kBuckets - 1;
    else bk = (int64_t)t;
    int64_t s = lut[bk > 0 ? bk - 1 : 0];
    int64_t e = lut[bk + 2 <= kBuckets ? bk + 2 : kBuckets];
    return s + lb_branchless(b + s, e - s, v);
  }
};

template <typename XT, typename OT>
static void bin_columns_loop(const XT* x, const double* bounds,
                             const int64_t* lengths, OT* out,
                             npy_intp n, npy_intp F, npy_intp L) {
  /* row-block x feature tiling: one feature's bounds + LUT stay
   * L1-resident for the whole inner row loop; the X/out blocks stay
   * L2-resident across features. */
  std::vector<BinLut> luts((size_t)F);
  for (npy_intp j = 0; j < F; j++) luts[(size_t)j].build(bounds + j * L,
                                                         lengths[j]);
  const npy_intp RB = 8192;
  for (npy_intp r0 = 0; r0 < n; r0 += RB) {
    npy_intp r1 = r0 + RB < n ? r0 + RB : n;
    for (npy_intp j = 0; j < F; j++) {
      const double* b = bounds + j * L;
      const int64_t lj = lengths[j];
      const BinLut& lut = luts[(size_t)j];
      if (lut.usable) {
        for (npy_intp r = r0; r < r1; r++) {
          double v = (double)x[r * F + j];
          if (std::isnan(v)) { out[r * F + j] = 0; continue; }
          /* values beyond the finite range short-circuit: below the first
           * bound -> bin 1; at/above the last finite bound the only
           * remaining candidates are the top two bounds */
          int64_t c;
          if (v <= lut.lo) c = (b[0] < v);
          else c = lut.find(b, lj, v);
          out[r * F + j] = (OT)(c + 1);
        }
      } else {
        for (npy_intp r = r0; r < r1; r++) {
          double v = (double)x[r * F + j];
          if (std::isnan(v)) { out[r * F + j] = 0; continue; }
          out[r * F + j] = (OT)(lb_branchless(b, lj, v) + 1);
        }
      }
    }
  }
}

static PyObject* py_bin_columns(PyObject*, PyObject* args) {
  PyObject *xo, *bo, *lo;
  int want_u16;
  if (!PyArg_ParseTuple(args, "OOOi", &xo, &bo, &lo, &want_u16))
    return nullptr;
  PyArrayObject* X = (PyArrayObject*)PyArray_FROM_OF(
      xo, NPY_ARRAY_IN_ARRAY);
  if (!X) return nullptr;
  int xt = PyArray_TYPE(X);
  if (PyArray_NDIM(X) != 2 || (xt != NPY_FLOAT32 && xt != NPY_FLOAT64)) {
    Py_DECREF(X);
    PyErr_SetString(PyExc_TypeError,
                    "bin_columns expects a 2-D float32/float64 matrix");
    return nullptr;
  }
  PyArrayObject* B = (PyArrayObject*)PyArray_FROM_OTF(
      bo, NPY_FLOAT64, NPY_ARRAY_IN_ARRAY);
  PyArrayObject* Ln = (PyArrayObject*)PyArray_FROM_OTF(
      lo, NPY_INT64, NPY_ARRAY_IN_ARRAY);
  if (!B || !Ln) { Py_XDECREF(B); Py_XDECREF(Ln); Py_DECREF(X); return nullptr; }
  npy_intp n = PyArray_DIM(X, 0), F = PyArray_DIM(X, 1);
  if (PyArray_NDIM(B) != 2 || PyArray_DIM(B, 0) != F ||
      PyArray_NDIM(Ln) != 1 || PyArray_DIM(Ln, 0) != F) {
    Py_DECREF(X); Py_DECREF(B); Py_DECREF(Ln);
    PyErr_SetString(PyExc_ValueError,
                    "bounds must be (F, L) and lengths (F,)");
    return nullptr;
  }
  npy_intp L = PyArray_DIM(B, 1);
  const int64_t* lens = (const int64_t*)PyArray_DATA(Ln);
  for (npy_intp j = 0; j < F; j++) {
    if (lens[j] < 1 || lens[j] > L) {
      Py_DECREF(X); Py_DECREF(B); Py_DECREF(Ln);
      PyErr_SetString(PyExc_ValueError, "lengths out of [1, L]");
      return nullptr;
    }
  }
  npy_intp dims[2] = {n, F};
  PyArrayObject* out = (PyArrayObject*)PyArray_SimpleNew(
      2, dims, want_u16 ? NPY_UINT16 : NPY_UINT8);
  if (!out) { Py_DECREF(X); Py_DECREF(B); Py_DECREF(Ln); return nullptr; }
  const double* bd = (const double*)PyArray_DATA(B);
  Py_BEGIN_ALLOW_THREADS
  if (xt == NPY_FLOAT32) {
    if (want_u16)
      bin_columns_loop((const float*)PyArray_DATA(X), bd, lens,
                       (uint16_t*)PyArray_DATA(out), n, F, L);
    else
      bin_columns_loop((const float*)PyArray_DATA(X), bd, lens,
                       (uint8_t*)PyArray_DATA(out), n, F, L);
  } else {
    if (want_u16)
      bin_columns_loop((const double*)PyArray_DATA(X), bd, lens,
                       (uint16_t*)PyArray_DATA(out), n, F, L);
    else
      bin_columns_loop((const double*)PyArray_DATA(X), bd, lens,
                       (uint8_t*)PyArray_DATA(out), n, F, L);
  }
  Py_END_ALLOW_THREADS
  Py_DECREF(X); Py_DECREF(B); Py_DECREF(Ln);
  return (PyObject*)out;
}

/* parse_libsvm(data: bytes) ->
 *   (float64 labels[n], int64 qids[n], int64 indptr[n+1],
 *    int32 indices[nnz], float32 values[nnz])
 * LightGBM's text format: "label [qid:Q] idx:val idx:val ... [# comment]".
 * qid is -1 for rows without one. The input MUST be a bytes object (its
 * buffer is NUL-terminated, which strtod/strtol parsing relies on). */
static PyObject* py_parse_libsvm(PyObject*, PyObject* args) {
  PyObject* bytes_obj;
  if (!PyArg_ParseTuple(args, "S", &bytes_obj)) return nullptr;
  const char* s = PyBytes_AS_STRING(bytes_obj);
  const char* end = s + PyBytes_GET_SIZE(bytes_obj);

  std::vector<double> labels;
  std::vector<int64_t> qids;
  std::vector<int64_t> indptr;
  std::vector<int32_t> indices;
  std::vector<float> values;
  indptr.push_back(0);

  const char* p = s;
  while (p < end) {
    const char* eol = (const char*)memchr(p, '\n', (size_t)(end - p));
    if (!eol) eol = end;
    const char* hash = (const char*)memchr(p, '#', (size_t)(eol - p));
    const char* lend = hash ? hash : eol;
    const char* q = p;
    while (q < lend && (*q == ' ' || *q == '\t' || *q == '\r')) q++;
    if (q >= lend) { p = eol + 1; continue; }  /* blank / comment-only */

    char* next;
    /* PyOS_string_to_double is locale-independent (strtod reads ',' as the
     * decimal point under e.g. de_DE, diverging from the Python fallback) */
    double lab = PyOS_string_to_double(q, &next, nullptr);
    if (PyErr_Occurred()) PyErr_Clear();
    if (next == q || next > lend) {
      PyErr_Format(PyExc_ValueError, "libsvm: bad label at byte %zd",
                   (Py_ssize_t)(q - s));
      return nullptr;
    }
    q = next;
    int64_t qid = -1;
    while (q < lend) {
      while (q < lend && (*q == ' ' || *q == '\t' || *q == '\r')) q++;
      if (q >= lend) break;
      if (lend - q >= 4 && memcmp(q, "qid:", 4) == 0) {
        q += 4;
        qid = (int64_t)strtoll(q, &next, 10);
        if (next == q || next > lend) {  /* bound: strtoll would skip '\n'
                                          * and eat the NEXT line's label */
          PyErr_Format(PyExc_ValueError, "libsvm: bad qid at byte %zd",
                       (Py_ssize_t)(q - s));
          return nullptr;
        }
        q = next;
        continue;
      }
      long long idx = strtoll(q, &next, 10);
      if (next == q || next >= lend || *next != ':') {
        PyErr_Format(PyExc_ValueError,
                     "libsvm: bad feature token at byte %zd",
                     (Py_ssize_t)(q - s));
        return nullptr;
      }
      if (idx < 0 || idx > 0x7fffffffLL) {
        /* an unchecked (int32_t) cast would silently wrap 2^32+1 -> 1 and
         * write the value into the wrong feature */
        PyErr_Format(PyExc_ValueError,
                     "libsvm: feature index %lld out of int32 range at "
                     "byte %zd", idx, (Py_ssize_t)(q - s));
        return nullptr;
      }
      q = next + 1;
      double v = PyOS_string_to_double(q, &next, nullptr);
      if (PyErr_Occurred()) PyErr_Clear();
      if (next == q) {
        PyErr_Format(PyExc_ValueError, "libsvm: bad value at byte %zd",
                     (Py_ssize_t)(q - s));
        return nullptr;
      }
      q = next;
      indices.push_back((int32_t)idx);
      values.push_back((float)v);
    }
    labels.push_back(lab);
    qids.push_back(qid);
    indptr.push_back((int64_t)indices.size());
    p = eol + 1;
  }

  npy_intp n = (npy_intp)labels.size();
  npy_intp np1 = n + 1;
  npy_intp nnz = (npy_intp)indices.size();
  PyArrayObject* a_lab = (PyArrayObject*)PyArray_SimpleNew(1, &n, NPY_FLOAT64);
  PyArrayObject* a_qid = (PyArrayObject*)PyArray_SimpleNew(1, &n, NPY_INT64);
  PyArrayObject* a_ptr = (PyArrayObject*)PyArray_SimpleNew(1, &np1, NPY_INT64);
  PyArrayObject* a_idx = (PyArrayObject*)PyArray_SimpleNew(1, &nnz, NPY_INT32);
  PyArrayObject* a_val = (PyArrayObject*)PyArray_SimpleNew(1, &nnz, NPY_FLOAT32);
  if (!a_lab || !a_qid || !a_ptr || !a_idx || !a_val) {
    Py_XDECREF(a_lab); Py_XDECREF(a_qid); Py_XDECREF(a_ptr);
    Py_XDECREF(a_idx); Py_XDECREF(a_val);
    return nullptr;
  }
  if (n) {
    std::memcpy(PyArray_DATA(a_lab), labels.data(), (size_t)n * 8);
    std::memcpy(PyArray_DATA(a_qid), qids.data(), (size_t)n * 8);
  }
  std::memcpy(PyArray_DATA(a_ptr), indptr.data(), (size_t)np1 * 8);
  if (nnz) {
    std::memcpy(PyArray_DATA(a_idx), indices.data(), (size_t)nnz * 4);
    std::memcpy(PyArray_DATA(a_val), values.data(), (size_t)nnz * 4);
  }
  return Py_BuildValue("(NNNNN)", a_lab, a_qid, a_ptr, a_idx, a_val);
}

static PyMethodDef Methods[] = {
    {"murmur3", py_murmur3, METH_VARARGS, "murmur3(data: bytes, seed) -> uint32"},
    {"murmur3_batch", py_murmur3_batch, METH_VARARGS,
     "murmur3_batch(seq_of_bytes, seed, mask) -> uint32[n]"},
    {"pad_sparse", py_pad_sparse, METH_VARARGS,
     "pad_sparse(rows, K) -> (int32[n,K], float32[n,K])"},
    {"stack_rows", py_stack_rows, METH_VARARGS,
     "stack_rows(seq, d) -> float32[n,d]"},
    {"parse_libsvm", py_parse_libsvm, METH_VARARGS,
     "parse_libsvm(data: bytes) -> (labels, qids, indptr, indices, values)"},
    {"bin_columns", py_bin_columns, METH_VARARGS,
     "bin_columns(X, bounds, lengths, want_u16) -> uint8/uint16[n,F]"},
    {nullptr, nullptr, 0, nullptr}};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_fastpath", nullptr, -1, Methods,
    nullptr, nullptr, nullptr, nullptr};

PyMODINIT_FUNC PyInit__fastpath(void) {
  import_array();
  return PyModule_Create(&moduledef);
}
