"""Native host-side fast paths with pure-Python fallback.

``fastpath.cpp`` is compiled on demand with the system C++ toolchain into a
CPython extension (no pybind11 needed). If compilation is unavailable the
same API is served by numpy/pure-Python implementations, so the package has
no hard native dependency — mirroring the reference's NativeLoader pattern
(``core/.../core/env/NativeLoader.java``) of shipping a loadable native
payload behind a stable interface.

API:
    available() -> bool
    murmur3(data: bytes, seed: int) -> int
    murmur3_batch(seq_of_bytes, seed, mask) -> np.uint32[n]
    pad_sparse(rows, K) -> (np.int32[n,K], np.float32[n,K])
    stack_rows(seq_of_float_vectors, d) -> np.float32[n,d]
    bin_columns(X, bounds, lengths, want_u16) -> np.uint8/uint16[n,F]
"""

from __future__ import annotations

import os
import subprocess
import sys
import sysconfig

import numpy as np

__all__ = ["available", "bin_columns", "murmur3", "murmur3_batch",
           "pad_sparse", "parse_libsvm", "stack_rows"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "fastpath.cpp")
_SO = os.path.join(_HERE, f"_fastpath{sysconfig.get_config_var('EXT_SUFFIX')}")

_impl = None


def _compile() -> bool:
    """Build the extension in place; returns success."""
    try:
        include_py = sysconfig.get_paths()["include"]
        include_np = np.get_include()
        # build to a unique temp name, then atomically publish: concurrent
        # importers on a shared filesystem never see a half-written .so
        tmp = f"{_SO}.{os.getpid()}.tmp"
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
               f"-I{include_py}", f"-I{include_np}", _SRC, "-o", tmp]
        res = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if res.returncode != 0 or not os.path.exists(tmp):
            return False
        os.replace(tmp, _SO)
        return True
    except Exception:
        return False


def _load():
    global _impl
    if _impl is not None:
        return _impl
    if os.environ.get("MMLSPARK_TPU_NO_NATIVE") == "1":
        _impl = False
        return _impl
    # a shipped .so without the source is fine — only rebuild when the
    # source exists and is newer than the binary
    usable = os.path.exists(_SO) and (
        not os.path.exists(_SRC)
        or os.path.getmtime(_SO) >= os.path.getmtime(_SRC))
    if not usable and not (os.path.exists(_SRC) and _compile()):
        _impl = False
        return _impl
    try:
        sys.path.insert(0, _HERE)
        import _fastpath  # noqa
        _impl = _fastpath
    except Exception:
        _impl = False
    finally:
        if _HERE in sys.path:
            sys.path.remove(_HERE)
    return _impl


def available() -> bool:
    return bool(_load())


# -- dispatching wrappers ----------------------------------------------------

def murmur3(data: bytes, seed: int = 0) -> int:
    impl = _load()
    if impl:
        return impl.murmur3(data, seed & 0xFFFFFFFF)
    from ..vw.murmur import _murmur3_32_py
    return _murmur3_32_py(data, seed)


def murmur3_batch(items, seed: int, mask: int) -> np.ndarray:
    impl = _load()
    if impl:
        return impl.murmur3_batch(list(items), seed & 0xFFFFFFFF, mask)
    from ..vw.murmur import _murmur3_32_py
    return np.asarray([_murmur3_32_py(b, seed) & mask for b in items],
                      dtype=np.uint32)


def pad_sparse(rows, K: int):
    impl = _load()
    if impl:
        return impl.pad_sparse(list(rows), int(K))
    n = len(rows)
    idx = np.zeros((n, K), dtype=np.int32)
    val = np.zeros((n, K), dtype=np.float32)
    for i, (ri, rv) in enumerate(rows):
        ri = np.asarray(ri)
        rv = np.asarray(rv)
        k = min(len(ri), len(rv), K)   # clamp like the native path
        idx[i, :k] = ri[:k].astype(np.int64)
        val[i, :k] = rv[:k]
    return idx, val


def parse_libsvm(data: bytes):
    """LightGBM-style libsvm text → CSR pieces:
    (labels f64[n], qids i64[n] (-1 = absent), indptr i64[n+1],
    indices i32[nnz], values f32[nnz])."""
    impl = _load()
    if impl:
        return impl.parse_libsvm(bytes(data))
    labels, qids, indices, values = [], [], [], []
    indptr = [0]
    for line in bytes(data).decode("utf-8", "replace").splitlines():
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        toks = line.split()
        labels.append(float(toks[0]))
        qid = -1
        for t in toks[1:]:
            k, _, v = t.partition(":")
            if not _:
                raise ValueError(f"libsvm: bad feature token {t!r}")
            if k == "qid":
                qid = int(v)
                continue
            ki = int(k)
            if not (0 <= ki <= 0x7FFFFFFF):
                # match the native parser: no silent int32 wraparound
                raise ValueError(f"libsvm: feature index {ki} out of "
                                 "int32 range")
            indices.append(ki)
            values.append(float(v))
        qids.append(qid)
        indptr.append(len(indices))
    return (np.asarray(labels, np.float64), np.asarray(qids, np.int64),
            np.asarray(indptr, np.int64), np.asarray(indices, np.int32),
            np.asarray(values, np.float32))


def bin_columns(X: np.ndarray, bounds: np.ndarray, lengths: np.ndarray,
                want_u16: bool) -> np.ndarray:
    """Quantile-bin a float matrix: ``searchsorted(bounds_j, x, "left") + 1``
    per element with NaN → bin 0. ``bounds`` is the (F, L) padded table,
    ``lengths`` the per-feature bound counts. The native loop replaces 28
    per-column ``np.searchsorted`` passes — the dataset-construction cost
    LightGBM pays in C++ (``LGBM_DatasetCreateFromMat``)."""
    impl = _load()
    if impl:
        return impl.bin_columns(np.ascontiguousarray(X), bounds, lengths,
                                int(bool(want_u16)))
    n, f = X.shape
    dtype = np.uint16 if want_u16 else np.uint8
    out = np.zeros((n, f), dtype=dtype)
    is_float = X.dtype.kind == "f"
    for j in range(f):
        col = X[:, j]
        binned = np.searchsorted(bounds[j, :lengths[j]], col,
                                 side="left") + 1
        if is_float:
            binned = np.where(np.isnan(col), 0, binned)
        out[:, j] = binned.astype(dtype)
    return out


def stack_rows(rows, d: int) -> np.ndarray:
    impl = _load()
    if impl:
        return impl.stack_rows(list(rows), int(d))
    out = np.zeros((len(rows), d), dtype=np.float32)
    for i, r in enumerate(rows):
        a = np.asarray(r, dtype=np.float32).ravel()
        k = min(len(a), d)
        out[i, :k] = a[:k]
    return out
