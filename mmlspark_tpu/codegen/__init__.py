"""Generated API surface (L6).

The reference generates its entire user-facing surface from stage reflection:
every stage mixes in ``Wrappable`` and PySpark/R wrapper classes are emitted
from Scala reflection over the Param system
(``core/src/main/scala/com/microsoft/azure/synapse/ml/codegen/Wrappable.scala:68-180``,
``codegen/CodeGen.scala:29-43``, type mapping ``ParamInfo`` ``Wrappable.scala:20-65``).

In a Python-first framework the moral equivalent is not a second Python
wrapper layer (the stages *are* Python) but the typed surface around them:

* **PEP 561 type stubs** (``.pyi``) for every module that defines pipeline
  stages — typed param attributes, fully-typed keyword constructors
  (``Literal`` for choice params), generated from the same reflective scan
  the fuzzing coverage gate uses.
* **API reference docs** (markdown) — one page per subpackage with a
  per-stage param table (name, type, default, doc), the analogue of the
  generated doc surface under ``website/``.

``python -m mmlspark_tpu.codegen`` regenerates both; a freshness test fails
if the checked-in surface drifts from the code (the analogue of the codegen
CI job in ``pipeline.yaml``).
"""

from __future__ import annotations

import importlib
import inspect
import pkgutil
from typing import Dict, List, Optional, Tuple

from mmlspark_tpu.core.params import ComplexParam, Param, Params
from mmlspark_tpu.core.pipeline import Estimator, Model, PipelineStage, Transformer

__all__ = [
    "discover_stages",
    "param_annotation",
    "generate_all_stubs",
    "generate_docs",
    "write_surface",
]


def discover_stages() -> List[type]:
    """Import every mmlspark_tpu module and return all PipelineStage
    subclasses, sorted by (module, qualname).

    The reflective scan plays the role of ``JarLoadingUtils`` in the
    reference (``core/utils/JarLoadingUtils``), which codegen and the
    fuzzing coverage gate both rely on.
    """
    import mmlspark_tpu

    for m in pkgutil.walk_packages(mmlspark_tpu.__path__, "mmlspark_tpu."):
        importlib.import_module(m.name)
    seen = {}

    def walk(cls):
        for sub in cls.__subclasses__():
            if sub.__module__.startswith("mmlspark_tpu"):
                seen[sub] = True
            walk(sub)

    walk(PipelineStage)
    return sorted(seen, key=lambda c: (c.__module__, c.__qualname__))


# ---------------------------------------------------------------------------
# Param → annotation mapping (the ParamInfo table, Wrappable.scala:20-65)
# ---------------------------------------------------------------------------

_BASIC = {int: "int", float: "float", bool: "bool", str: "str",
          dict: "Dict[str, Any]", list: "List[Any]", None: "Any"}


def param_annotation(p: Param) -> str:
    """Annotation string for a param value, e.g. ``Optional[str]`` or
    ``Literal['serial', 'data_parallel', 'voting_parallel']``."""
    if isinstance(p, ComplexParam):
        return "Any"
    if p.choices is not None and all(isinstance(c, str) for c in p.choices):
        inner = ", ".join(repr(c) for c in p.choices)
        ann = f"Literal[{inner}]"
    elif isinstance(p.dtype, tuple) and len(p.dtype) == 2 and p.dtype[0] is list:
        ann = f"List[{_BASIC.get(p.dtype[1], 'Any')}]"
    elif isinstance(p.dtype, tuple) and len(p.dtype) == 2 \
            and p.dtype[1] is list:
        # (X, list) = scalar-or-list-of-X (e.g. one metric name or several)
        base = _BASIC.get(p.dtype[0], 'Any')
        ann = f"Union[{base}, List[{base}]]"
    else:
        ann = _BASIC.get(p.dtype, "Any")
    if p.has_default and p.default is None and ann not in ("Any",):
        ann = f"Optional[{ann}]"
    return ann


def _closure_for_stubs(stages: List[type]) -> Dict[str, List[type]]:
    """{stub module: classes to emit}. A stub shadows its whole module for
    type checkers, so every base class defined in a stubbed module must be
    emitted there too (bases living in un-stubbed modules resolve through
    their real source)."""
    stub_modules = {c.__module__ for c in stages}
    emit: Dict[str, Dict[type, bool]] = {m: {} for m in stub_modules}
    for c in stages:
        emit[c.__module__][c] = True
    frontier = list(stages)
    while frontier:
        cls = frontier.pop()
        for b in cls.__bases__:
            if b is object or not b.__module__.startswith("mmlspark_tpu"):
                continue
            if b.__module__ in stub_modules and b not in emit[b.__module__]:
                emit[b.__module__][b] = True
                frontier.append(b)
    out = {}
    for m, classes in emit.items():
        cs = list(classes)
        order = {c: i for i, c in enumerate(sorted(
            cs, key=lambda c: c.__qualname__))}
        out[m] = sorted(cs, key=lambda c: (len(c.__mro__), order[c]))
    return out


def _base_decl(cls: type, emitted_here: set) -> Tuple[str, List[Tuple[str, str]]]:
    """Return (bases-string, imports) for a class declaration in a stub."""
    names, imports = [], []
    for b in cls.__bases__:
        if b is object:
            continue
        names.append(b.__name__)
        if b.__module__ != cls.__module__ and b.__name__ not in emitted_here:
            imports.append((b.__module__, b.__name__))
    return ", ".join(names) or "Params", imports


def _public_functions(module) -> List:
    out = []
    for name, obj in sorted(vars(module).items()):
        if name.startswith("_") or not inspect.isfunction(obj):
            continue
        if obj.__module__ != module.__name__:
            continue
        out.append(obj)
    return out


def _fn_stub(fn) -> str:
    """Permissive signature stub for a module-level function."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return f"def {fn.__name__}(*args: Any, **kwargs: Any) -> Any: ..."
    parts = []
    for p in sig.parameters.values():
        if p.kind is inspect.Parameter.VAR_POSITIONAL:
            parts.append(f"*{p.name}: Any")
        elif p.kind is inspect.Parameter.VAR_KEYWORD:
            parts.append(f"**{p.name}: Any")
        elif p.default is not inspect.Parameter.empty:
            parts.append(f"{p.name}: Any = ...")
        else:
            parts.append(f"{p.name}: Any")
    return f"def {fn.__name__}({', '.join(parts)}) -> Any: ..."


def _init_stub(cls: type) -> str:
    """Constructor stub. Classes with a custom ``__init__`` keep their real
    positional parameters (``ONNXModel(model_bytes, ...)`` must type-check);
    declared params not in the signature become typed keyword-only args."""
    params = cls.params()
    own_init = cls.__init__ is not Params.__init__
    pos_parts, kw_only, seen = [], [], set()
    if own_init:
        try:
            sig = inspect.signature(cls.__init__)
        except (TypeError, ValueError):
            sig = None
        if sig is not None:
            for p in list(sig.parameters.values())[1:]:  # drop self
                if p.kind is inspect.Parameter.VAR_KEYWORD:
                    continue
                if p.kind is inspect.Parameter.VAR_POSITIONAL:
                    pos_parts.append(f"*{p.name}: Any")
                    continue
                ann = (param_annotation(params[p.name])
                       if p.name in params else "Any")
                default = " = ..." if p.default is not inspect.Parameter.empty \
                    else ""
                if p.kind is inspect.Parameter.KEYWORD_ONLY:
                    kw_only.append(f"{p.name}: {ann}{default}")
                else:
                    pos_parts.append(f"{p.name}: {ann}{default}")
                seen.add(p.name)
    kw_parts = kw_only + [f"{n}: {param_annotation(params[n])} = ..."
                          for n in sorted(params) if n not in seen]
    parts = ["self"] + pos_parts
    if kw_parts:
        if not any(p.startswith("*") for p in pos_parts):
            parts.append("*")
        parts += kw_parts
    parts.append("**kwargs: Any")
    return f"    def __init__({', '.join(parts)}) -> None: ..."


#: typed signatures for the core stage API — a stub shadows its module, so
#: these must be re-declared wherever the real def is hidden by a stub
_KNOWN_METHODS = {
    "transform": ("    def transform(self, df: DataFrame, "
                  "params: Optional[dict] = ...) -> DataFrame: ..."),
    "fit": ("    def fit(self, df: DataFrame, "
            "params: Optional[dict] = ...) -> Model: ..."),
    "fit_multiple": ("    def fit_multiple(self, df: DataFrame, "
                     "param_maps: Any) -> List[Model]: ..."),
    "save": "    def save(self, path: str, overwrite: bool = ...) -> None: ...",
    "load": ("    @classmethod\n"
             "    def load(cls, path: str) -> PipelineStage: ..."),
}


def _generate_module_stub(module_name: str,
                         classes: List[type]) -> Optional[str]:
    """Generate ``.pyi`` text for one module from its emit-closure classes
    (stages plus any base classes other stubs reference here)."""
    if not classes:
        return None
    module = importlib.import_module(module_name)
    emitted_here = {c.__name__ for c in classes}
    imports: Dict[str, set] = {}
    bodies = []
    needs_core = False
    for cls in classes:
        bases, base_imports = _base_decl(cls, emitted_here)
        for mod, name in base_imports:
            imports.setdefault(mod, set()).add(name)
        lines = [f"class {cls.__name__}({bases}):"]
        doc = cls.__dict__.get("__doc__")  # own docstring only, not inherited
        if doc:
            first = doc.strip().splitlines()[0].strip().replace('"""', "'''")
            if first:
                lines.append(f'    """{first}"""')
        params = cls.params()
        for name in sorted(params):
            lines.append(f"    {name}: {param_annotation(params[name])}")
        lines.append(_init_stub(cls))
        for meth, sig in _KNOWN_METHODS.items():
            if meth in cls.__dict__:
                lines.append(sig)
                needs_core = True
        # methods whose defs this stub hides resolve as Any, not as errors
        lines.append("    def __getattr__(self, name: str) -> Any: ...")
        bodies.append("\n".join(lines))
    if needs_core:
        imports.setdefault("mmlspark_tpu.core.dataframe", set()).add("DataFrame")
        for name in ("Model", "PipelineStage"):
            if name not in emitted_here:
                imports.setdefault("mmlspark_tpu.core.pipeline", set()).add(name)
    for fn in _public_functions(module):
        bodies.append(_fn_stub(fn))

    header = [
        "# AUTO-GENERATED by `python -m mmlspark_tpu.codegen` — do not edit.",
        "# Typed surface for the Param system; parity role of the reference's",
        "# generated PySpark wrappers (codegen/Wrappable.scala:68-180).",
        "from typing import Any, Dict, List, Literal, Optional, Union",
        "",
    ]
    imports.setdefault("mmlspark_tpu.core.params", set()).add("Params")
    for mod in sorted(imports):
        if mod == module_name:
            continue
        names = ", ".join(sorted(imports[mod]))
        header.append(f"from {mod} import {names}")
    footer = ["", "def __getattr__(name: str) -> Any: ...", ""]
    return "\n".join(header + [""] + ["\n\n".join(bodies)] + footer)


def generate_all_stubs(stages: Optional[List[type]] = None) -> Dict[str, str]:
    """{module_name: stub_text} for every module defining stages."""
    if stages is None:
        stages = discover_stages()
    closure = _closure_for_stubs(stages)
    out = {}
    for module_name in sorted(closure):
        text = _generate_module_stub(module_name, closure[module_name])
        if text:
            out[module_name] = text
    return out


# ---------------------------------------------------------------------------
# Docs generation
# ---------------------------------------------------------------------------

def _fmt_default(p: Param) -> str:
    if not p.has_default:
        return "*(required)*"
    if isinstance(p, ComplexParam):
        return "—"
    return f"`{p.default!r}`"


def _stage_doc(cls: type) -> str:
    lines = [f"### `{cls.__name__}`", ""]
    kind = ("Estimator" if issubclass(cls, Estimator)
            else "Model" if issubclass(cls, Model)
            else "Transformer" if issubclass(cls, Transformer)
            else "Stage")
    lines.append(f"*{kind}* — `{cls.__module__}.{cls.__qualname__}`")
    lines.append("")
    doc = inspect.getdoc(cls)
    if doc:
        lines.append(doc.split("\n\n")[0].strip())
        lines.append("")
    params = cls.params()
    if params:
        lines.append("| param | type | default | doc |")
        lines.append("|---|---|---|---|")
        for name in sorted(params):
            p = params[name]
            d = (p.doc or "").replace("|", "\\|")
            lines.append(f"| `{name}` | `{param_annotation(p)}` | "
                         f"{_fmt_default(p)} | {d} |")
        lines.append("")
    return "\n".join(lines)


def generate_docs(stages: Optional[List[type]] = None) -> Dict[str, str]:
    """{subpackage: markdown} API reference, one page per subpackage."""
    if stages is None:
        stages = discover_stages()
    by_pkg: Dict[str, List[type]] = {}
    for c in stages:
        pkg = c.__module__.split(".")[1]
        by_pkg.setdefault(pkg, []).append(c)
    pages = {}
    for pkg in sorted(by_pkg):
        classes = sorted(by_pkg[pkg], key=lambda c: c.__qualname__)
        lines = [f"# `mmlspark_tpu.{pkg}` API reference", "",
                 "*Generated by `python -m mmlspark_tpu.codegen` — do not edit.*",
                 ""]
        for cls in classes:
            lines.append(_stage_doc(cls))
        pages[pkg] = "\n".join(lines)
    index = ["# API reference", "",
             "*Generated by `python -m mmlspark_tpu.codegen`.*", "",
             "| package | stages |", "|---|---|"]
    for pkg in sorted(by_pkg):
        index.append(f"| [`mmlspark_tpu.{pkg}`]({pkg}.md) | {len(by_pkg[pkg])} |")
    pages["index"] = "\n".join(index) + "\n"
    return pages


# ---------------------------------------------------------------------------
# R wrapper generation — the RWrappable role (``Wrappable.scala:93``,
# package assembly ``CodeGen.scala:66-120``). The reference's generated R
# functions drive JVM stages through sparklyr; here they drive the Python
# stages through reticulate, from the same Param reflection as the stubs.
# ---------------------------------------------------------------------------

def _r_fn_name(cls: type) -> str:
    """``LightGBMClassifier`` → ``sml_light_gbm_classifier`` (the reference
    prefixes generated R functions ``ml_``, ``Wrappable.scala:100-109``).
    Acronym runs split before their last capital (GBMClassifier →
    gbm_classifier)."""
    import re
    snake = re.sub(r"(?<=[a-z0-9])(?=[A-Z])|(?<=[A-Z])(?=[A-Z][a-z])", "_",
                   cls.__name__).lower()
    return "sml_" + snake


def _r_literal(v) -> Optional[str]:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, int):
        return f"{v}L"
    if isinstance(v, float):
        return repr(v) if v == v and abs(v) != float("inf") else "NULL"
    if isinstance(v, str):
        return '"' + v.replace("\\", "\\\\").replace('"', '\\"') + '"'
    if isinstance(v, (list, tuple)) and all(
            isinstance(x, (int, float, str, bool)) for x in v):
        items = [_r_literal(x) for x in v]
        return "c(" + ", ".join(i for i in items if i) + ")" if items \
            else "NULL"
    return "NULL"       # dicts / complex values: settable but no default


def _r_stage_fn(cls: type) -> Optional[str]:
    if cls.__qualname__ != cls.__name__:
        return None     # nested classes are not part of the R surface
    params = cls.params()
    sig_parts, conv_parts, doc_lines = [], [], []
    for name in sorted(params):
        p = params[name]
        default = _r_literal(p.default) if p.has_default and not isinstance(
            p, ComplexParam) else ("NULL" if isinstance(p, ComplexParam)
                                   or not p.has_default else "NULL")
        sig_parts.append(f"{name} = {default}")
        rhs = f"as.integer({name})" if p.dtype is int else name
        conv_parts.append(f"    {name} = if (!is.null({name})) {rhs}")
        doc = (p.doc or "").strip().splitlines()
        doc_lines.append(f"#' @param {name} {doc[0] if doc else ''}".rstrip())
    path = "$".join(cls.__module__.split(".")[1:] + [cls.__name__])
    fn = _r_fn_name(cls)
    title = (cls.__dict__.get("__doc__") or cls.__name__).strip() \
        .splitlines()[0].replace("\\", "\\\\")
    body = [f"#' {title}", "#'"] + doc_lines + [
        "#' @export",
        f"{fn} <- function({', '.join(sig_parts)}) {{",
        "  args <- .sml_drop_null(list(",
        ",\n".join(conv_parts),
        "  ))",
        f"  do.call(.sml_module()${path}, args)",
        "}",
    ]
    return "\n".join(body)


_R_ZZZ = '''\
# AUTO-GENERATED by `python -m mmlspark_tpu.codegen` - do not edit.
# Runtime plumbing for the generated wrappers: the Python package is
# reached through reticulate (the JVM/sparklyr role in the reference,
# codegen/CodeGen.scala:66-120).

.sml_env <- new.env(parent = emptyenv())

.sml_module <- function() {
  if (is.null(.sml_env$module)) {
    .sml_env$module <- reticulate::import("mmlspark_tpu", delay_load = TRUE)
  }
  .sml_env$module
}

.sml_drop_null <- function(args) {
  Filter(Negate(is.null), args)
}

#' Transform a data.frame with a fitted stage
#' @export
sml_transform <- function(stage, df) {
  interop <- reticulate::import("mmlspark_tpu.interop")
  interop$transform_pandas(stage, df)
}

#' Fit an estimator on a data.frame
#' @export
sml_fit <- function(estimator, df) {
  interop <- reticulate::import("mmlspark_tpu.interop")
  interop$fit_pandas(estimator, df)
}
'''


def generate_r_wrappers(stages: Optional[List[type]] = None) -> Dict[str, str]:
    """{relative path under r/mmlsparktpu: file text} — one R file per
    subpackage plus DESCRIPTION/NAMESPACE/zzz.R."""
    if stages is None:
        stages = discover_stages()
    by_pkg: Dict[str, List[type]] = {}
    for c in stages:
        by_pkg.setdefault(c.__module__.split(".")[1], []).append(c)
    files: Dict[str, str] = {"R/zzz.R": _R_ZZZ}
    exports = ["sml_transform", "sml_fit"]
    for pkg in sorted(by_pkg):
        fns = []
        for cls in sorted(by_pkg[pkg], key=lambda c: c.__qualname__):
            text = _r_stage_fn(cls)
            if text is not None:
                fns.append(text)
                exports.append(_r_fn_name(cls))
        if fns:
            header = ("# AUTO-GENERATED by `python -m mmlspark_tpu.codegen`"
                      " - do not edit.\n# R surface for mmlspark_tpu."
                      f"{pkg} (RWrappable role, Wrappable.scala:93).\n")
            files[f"R/{pkg}.R"] = header + "\n\n".join(fns) + "\n"
    files["DESCRIPTION"] = (
        "Package: mmlsparktpu\n"
        "Type: Package\n"
        "Title: R bindings for the mmlspark-tpu framework\n"
        "Version: 0.1.0\n"
        "Description: Generated wrappers driving mmlspark_tpu Python\n"
        "    stages through reticulate; the role of the reference's\n"
        "    generated sparklyr package.\n"
        "Imports: reticulate\n"
        "License: MIT\n"
        "Encoding: UTF-8\n")
    files["NAMESPACE"] = (
        "# AUTO-GENERATED by `python -m mmlspark_tpu.codegen` - do not edit.\n"
        + "".join(f"export({e})\n" for e in sorted(set(exports))))
    return files


def write_surface(repo_root: str) -> List[str]:
    """Write stubs next to their modules and docs under docs/api/.
    Returns the list of paths written."""
    import os

    written = []
    stages = discover_stages()  # one reflective scan feeds stubs and docs
    for module_name, text in generate_all_stubs(stages).items():
        mod = importlib.import_module(module_name)
        src = inspect.getsourcefile(mod)
        path = os.path.splitext(src)[0] + ".pyi"
        with open(path, "w") as f:
            f.write(text)
        written.append(path)
    docs_dir = os.path.join(repo_root, "docs", "api")
    os.makedirs(docs_dir, exist_ok=True)
    for page, text in generate_docs(stages).items():
        path = os.path.join(docs_dir, f"{page}.md")
        with open(path, "w") as f:
            f.write(text if text.endswith("\n") else text + "\n")
        written.append(path)
    # PEP 561 marker so type checkers honor the generated stubs
    marker = os.path.join(repo_root, "mmlspark_tpu", "py.typed")
    with open(marker, "w") as f:
        f.write("")
    written.append(marker)
    r_root = os.path.join(repo_root, "r", "mmlsparktpu")
    for rel, text in generate_r_wrappers(stages).items():
        path = os.path.join(r_root, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(text)
        written.append(path)
    return sorted(written)
