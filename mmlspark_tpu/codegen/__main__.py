"""Regenerate the typed API surface: ``python -m mmlspark_tpu.codegen``."""

import os

from mmlspark_tpu.codegen import write_surface


def main():
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    for path in write_surface(repo_root):
        print(path)


if __name__ == "__main__":
    main()
