"""Front-end interop: run fitted stages inside pandas and PySpark pipelines.

The reference's user surface *is* Spark — every stage is a Spark ML
``Transformer`` reached through generated PySpark wrappers
(``codegen/Wrappable.scala:68-180``), so ``DataFrame.transform`` composes
natively. This framework's pipelines are TPU-resident; interop goes the
other way: wrap a fitted stage so host dataframe ecosystems can call it.

* :func:`transform_pandas` / :func:`fit_pandas` — pandas in, pandas out.
* :func:`make_pandas_udf_fn` — a plain ``pd.DataFrame -> pd.DataFrame``
  closure suitable for ``pyspark.sql.functions.pandas_udf`` /
  ``DataFrame.mapInPandas`` / ``groupBy().applyInPandas``; the stage's
  model state is captured once and shipped to executors by closure
  serialization (the moral of the reference's broadcast-payload pattern,
  ``ONNXModel.scala:471-497``).
* :func:`spark_transform` — convenience: ``spark_df.mapInPandas`` wiring
  when pyspark is importable (gated; pyspark is not a dependency).
"""

from __future__ import annotations

from typing import Iterable, Optional

from ..core.dataframe import DataFrame
from ..core.pipeline import Estimator, Transformer

from .onnx_shim import install_onnx_shim, uninstall_onnx_shim

__all__ = ["transform_pandas", "fit_pandas", "make_pandas_udf_fn",
           "spark_transform", "spark_schema_for", "install_onnx_shim",
           "uninstall_onnx_shim"]


def transform_pandas(stage: Transformer, pdf, npartitions: int = 1):
    """pandas DataFrame → stage.transform → pandas DataFrame."""
    out = stage.transform(DataFrame.from_pandas(pdf, npartitions))
    return out.to_pandas()


def fit_pandas(estimator: Estimator, pdf, params: Optional[dict] = None,
               npartitions: int = 1):
    """Fit an estimator on a pandas DataFrame; returns the fitted Model."""
    return estimator.fit(DataFrame.from_pandas(pdf, npartitions), params)


def make_pandas_udf_fn(stage: Transformer, output_cols=None):
    """A ``pd.DataFrame -> pd.DataFrame`` function closing over the stage.

    Works as the body of ``mapInPandas`` (iterator variant handled by
    :func:`spark_transform`) or ``applyInPandas``. ``output_cols`` limits
    the returned columns (Spark needs a declared schema; see
    :func:`spark_schema_for`).
    """
    def apply_fn(pdf):
        out = transform_pandas(stage, pdf)
        return out[list(output_cols)] if output_cols else out

    return apply_fn


def _batch_iter_fn(stage: Transformer, output_cols=None):
    def map_batches(batches: Iterable):
        for pdf in batches:
            yield make_pandas_udf_fn(stage, output_cols)(pdf)

    return map_batches


def spark_schema_for(stage: Transformer, sample_pdf, output_cols=None):
    """Infer the output Spark schema by running the stage on a small pandas
    sample (the reference reads model metadata for this,
    ``ONNXModel.scala:606-653``; here a probe row is exact and cheap)."""
    from pyspark.sql.types import (ArrayType, BooleanType, DoubleType,
                                   FloatType, LongType, StringType,
                                   StructField, StructType)
    import numpy as np

    out = transform_pandas(stage, sample_pdf)
    if output_cols:
        out = out[list(output_cols)]

    def field_for(name, dtype, sample):
        # pandas extension dtypes (StringDtype etc.) are not numpy dtypes
        # and crash np.issubdtype — route them by the sample value instead
        if isinstance(dtype, np.dtype):
            if np.issubdtype(dtype, np.bool_):
                return StructField(name, BooleanType())
            if np.issubdtype(dtype, np.integer):
                return StructField(name, LongType())
            if np.issubdtype(dtype, np.float32):
                return StructField(name, FloatType())
            if np.issubdtype(dtype, np.floating):
                return StructField(name, DoubleType())
        if isinstance(sample, np.ndarray):
            elem = (FloatType() if sample.dtype == np.float32
                    else DoubleType() if np.issubdtype(sample.dtype,
                                                       np.floating)
                    else LongType())
            t = ArrayType(elem)
            for _ in range(sample.ndim - 1):
                t = ArrayType(t)
            return StructField(name, t)
        return StructField(name, StringType())

    fields = []
    for name in out.columns:
        col = out[name]
        sample = col.iloc[0] if len(col) else None
        fields.append(field_for(name, col.dtype, sample))
    return StructType(fields)


def spark_transform(stage: Transformer, spark_df, output_cols=None,
                    schema=None, sample_pdf=None):
    """Run a fitted stage over a **PySpark** DataFrame via ``mapInPandas``.

    ``schema`` (a StructType or DDL string) or ``sample_pdf`` (to infer it)
    must be provided. Gated on pyspark being importable — pyspark is an
    optional peer, not a dependency.
    """
    try:
        import pyspark  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "spark_transform requires pyspark on the driver; install it or "
            "use transform_pandas/make_pandas_udf_fn directly") from e
    if schema is None:
        if sample_pdf is None:
            raise ValueError("provide schema= or sample_pdf= to infer it")
        schema = spark_schema_for(stage, sample_pdf, output_cols)
    # ndarray cells must become lists for Spark's arrow conversion
    base = _batch_iter_fn(stage, output_cols)

    def map_batches(batches):
        import numpy as np

        def to_list(v):
            return v.tolist() if isinstance(v, np.ndarray) else v

        for out in base(batches):
            for c in out.columns:
                # per-cell: a null first row must not leave later ndarray
                # cells unconverted for arrow
                if out[c].dtype == object:
                    out[c] = out[c].map(to_list)
            yield out

    return spark_df.mapInPandas(map_batches, schema=schema)
