"""A minimal ``onnx`` module shim so ``torch.onnx.export`` works without
the onnx pip package.

torch's TorchScript exporter builds and serializes the complete ONNX
ModelProto in C++; it imports the ``onnx`` package at the very end only to
scan the graph for onnxscript custom functions
(``torch/onnx/_internal/torchscript_exporter/onnx_proto_utils.py:183``,
``_add_onnxscript_fn``). That scan needs exactly one API —
``onnx.load_model_from_string`` — plus protobuf-shaped read access
(``model.graph``, ``graph.node``, ``node.attribute``, ``attr.g``,
``node.domain``/``op_type``, ``model.functions``). This repo already parses
real ONNX protobufs (``onnx/proto.py``), so the shim simply routes torch's
import to it; the exporter then emits a GENUINE torch-serialized ONNX
model that ``convert_model`` consumes.

Parity context: the reference executes arbitrary exporter artifacts
through ORT (``deep-learning/.../onnx/ONNXModel.scala:195-245``); this
closes the "has never eaten a real exporter artifact" gap within a
zero-egress image.

Usage::

    from mmlspark_tpu.interop.onnx_shim import install_onnx_shim
    install_onnx_shim()
    torch.onnx.export(model, args, buffer, dynamo=False)

Scope: models with onnxscript custom functions would need proto
re-serialization and are rejected with a clear error; everything a stock
``nn.Module`` export produces passes through untouched.
"""

from __future__ import annotations

import sys
import types

from ..onnx.proto import (AttributeProto, GraphProto, ModelProto, NodeProto,
                          TensorProto, parse_model)

__all__ = ["install_onnx_shim", "uninstall_onnx_shim"]


def load_model_from_string(data: bytes) -> ModelProto:
    return parse_model(data)


def install_onnx_shim() -> types.ModuleType:
    """Register the shim as ``sys.modules['onnx']`` (no-op if a real onnx
    package is already imported). Returns the module either way."""
    existing = sys.modules.get("onnx")
    if existing is not None:
        return existing
    # defer to a REAL onnx package if one is installed but not yet
    # imported — shadowing it would cripple onnx.load/checker/helper for
    # the rest of the process
    import importlib.util
    if importlib.util.find_spec("onnx") is not None:
        import importlib
        return importlib.import_module("onnx")
    mod = types.ModuleType("onnx")
    mod.__doc__ = __doc__
    # a real ModuleSpec: probes like importlib.util.find_spec("onnx")
    # (transformers does this at import) choke on __spec__ = None
    import importlib.machinery
    mod.__spec__ = importlib.machinery.ModuleSpec("onnx", None)
    mod.__version__ = "0.0.0+mmlspark-tpu-shim"
    mod.load_model_from_string = load_model_from_string
    mod.ModelProto = ModelProto
    mod.GraphProto = GraphProto
    mod.NodeProto = NodeProto
    mod.AttributeProto = AttributeProto
    mod.TensorProto = TensorProto
    mod.__mmlspark_tpu_shim__ = True
    sys.modules["onnx"] = mod
    return mod


def uninstall_onnx_shim() -> None:
    mod = sys.modules.get("onnx")
    if mod is not None and getattr(mod, "__mmlspark_tpu_shim__", False):
        del sys.modules["onnx"]
