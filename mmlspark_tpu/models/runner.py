"""BatchRunner — the shared device feed/drain pipeline of the graph runners.

``ONNXModel`` and ``JaxModel`` used to each carry their own copy of the
partition loop, and both copies had the same three stalls: the first batch of
every padding bucket paid a full XLA compile inline, outputs drained at
partition end through serialized per-batch per-column ``np.asarray`` host
copies, and all coerce/pad host work ran on the dispatch thread. This module
is the one implementation both models now share, with the stalls engineered
out:

* **prefetch** — coerce/pad of batch k+1 runs on a background worker
  (:class:`~mmlspark_tpu.stages.batching.PrefetchIterator`, the
  ``DynamicBufferedBatcher`` producer machinery), bounded by
  ``prefetch_depth`` prepared batches of host memory;
* **async feed** — host→device transfers enqueue immediately at dispatch
  time, overlapping the previous batch's compute;
* **overlapped drain** — ``copy_to_host_async()`` is issued per output the
  moment a batch is dispatched, so device→host transfers overlap compute,
  and the partition-end drain is ONE batched ``jax.device_get`` over every
  pending output instead of a per-batch-per-column ``np.asarray`` loop.

Every stage is instrumented through :class:`~mmlspark_tpu.ops.compile_cache.
StageCounters` (coerce / pad / h2d / compile / dispatch / d2h), cheap enough
to stay on in production and surfaced by ``bench.py``.
"""

from __future__ import annotations

import threading

from ..reliability.lock_sanitizer import new_lock
import time
from typing import Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..core.residency import is_device_array, record_hit
from ..observability import charge as _ledger_charge
from ..observability import counter as _metric_counter
from ..observability import tracing as _tracing
from ..observability import watch as _watch
from ..ops.compile_cache import (M_CACHE_HITS, M_CACHE_MISSES,
                                 M_STEADY_RECOMPILES, StageCounters,
                                 jit_cache_size)
from ..ops.padding import bucket_size, pad_axis, pad_axis_device
from ..stages.batching import PrefetchIterator, batch_slices
from ..utils.profiling import span as _span

__all__ = ["BatchRunner", "StagingSlabPool"]

M_SLAB_ALLOCS = _metric_counter(
    "mmlspark_staging_slab_allocs_total",
    "host staging slabs allocated (first touch of a shape/dtype signature)")
M_SLAB_REUSE = _metric_counter(
    "mmlspark_staging_slab_reuse_total",
    "host staging slab acquisitions served from the pool")


class StagingSlabPool:
    """Reusable host staging buffers for the coerce/pad prefetch worker.

    Padding into a small circulating set of pre-touched slabs (instead of a
    fresh ``np.pad`` allocation per batch) is the host-side half of h2d
    overlap: the buffers are stable, faulted-in pages — the closest thing to
    pinned memory the numpy layer can express — so the async ``device_put``
    streams from warm memory while the next batch is being prepared. At most
    ``depth`` free slabs per (shape, dtype) signature are retained
    (double-buffered by default: one being transferred, one being filled);
    shape bucketing keeps the signature set tiny, so steady state allocates
    nothing.
    """

    def __init__(self, depth: int = 2):
        self.depth = max(1, int(depth))
        self._lock = new_lock("models.runner.StagingSlabPool._lock")
        self._free: Dict[tuple, List[np.ndarray]] = {}
        self._issued: set = set()
        self.allocs = 0
        self.reuses = 0

    def acquire(self, shape, dtype) -> np.ndarray:
        key = (tuple(shape), np.dtype(dtype).str)
        with self._lock:
            free = self._free.get(key)
            if free:
                buf = free.pop()
                self.reuses += 1
                M_SLAB_REUSE.inc()
            else:
                buf = np.empty(key[0], dtype=dtype)
                self.allocs += 1
                M_SLAB_ALLOCS.inc()
            self._issued.add(id(buf))
        return buf

    def release(self, arr) -> bool:
        """Return a slab to the pool; silently ignores foreign arrays, so
        callers can release every feed they dispatched."""
        if not isinstance(arr, np.ndarray):
            return False
        with self._lock:
            if id(arr) not in self._issued:
                return False
            self._issued.discard(id(arr))
            free = self._free.setdefault((arr.shape, arr.dtype.str), [])
            if len(free) < self.depth:
                free.append(arr)
            return True

    def stats(self) -> Dict[str, float]:
        with self._lock:
            total = self.allocs + self.reuses
            return {"allocs": self.allocs, "reuses": self.reuses,
                    "reuse_rate": (self.reuses / total) if total else None}


class BatchRunner:
    """Run one partition's rows through a jitted program in padded batches.

    ``coerce(sl) -> {feed name: host ndarray}`` is the model-specific part
    (column lookup, dtype coercion, reshape); everything downstream —
    padding, placement, dispatch, drain, instrumentation — is shared.
    """

    def __init__(self, jitted, params,
                 coerce: Callable[[slice], Dict[str, np.ndarray]],
                 put: Callable, shards: int = 1, mini_batch_size: int = 64,
                 prefetch_depth: int = 2,
                 counters: Optional[StageCounters] = None,
                 staging: Optional[StagingSlabPool] = None,
                 buckets: Optional[Tuple[int, ...]] = None,
                 tuning: str = "", model_sig: Optional[str] = None,
                 placement_key: str = "default"):
        self.jitted = jitted
        self.params = params
        self.coerce = coerce
        self.put = put
        self.shards = max(1, int(shards))
        self.mini_batch_size = max(1, int(mini_batch_size))
        self.prefetch_depth = max(0, int(prefetch_depth))
        self.counters = counters if counters is not None else StageCounters()
        # model-owned so slabs amortize across transform calls, not just
        # batches of one partition
        self.staging = staging
        # custom padding-bucket ladder (None = power-of-two default); the
        # ladder must cover the largest batch the runner can produce
        self.buckets = (None if not buckets
                        else tuple(sorted({int(b) for b in buckets})))
        if self.buckets and self.mini_batch_size > self.buckets[-1]:
            raise ValueError(
                f"mini_batch_size={self.mini_batch_size} exceeds the "
                f"largest bucket {self.buckets[-1]} of the ladder")
        if tuning not in ("", "auto"):
            raise ValueError(f"tuning must be '' or 'auto', got {tuning!r}")
        self.tuning = tuning
        self.model_sig = model_sig
        self.placement_key = str(placement_key)
        self._tuned = False           # "auto" resolved the store already
        self.decision = None          # the applied TuningDecision, if any
        self._samples: Dict[int, Dict[str, float]] = {}

    # -- tuning: consult the observation store, harvest samples back ---------
    def _resolve_auto(self, n_rows: int) -> None:
        """``tuning="auto"``: on first run, fit the observation store for
        this model signature and apply the picked config. A cold store is
        not an error — the defaults stand and this run's harvest becomes
        the training data a later process decides from."""
        self._tuned = True
        from ..tuning.cost_model import resolve_tuning
        decision = resolve_tuning(
            self.model_sig or "anonymous", self.placement_key,
            {int(n_rows): 1},
            defaults=(self.mini_batch_size, self.prefetch_depth))
        if decision is None:
            return
        self.decision = decision
        self.mini_batch_size = max(1, decision.mini_batch_size)
        self.prefetch_depth = max(0, decision.prefetch_depth)
        self.buckets = decision.buckets

    def _note_sample(self, padded: int, b: int, *, seconds: float = 0.0,
                     prep_seconds: float = 0.0, compile_seconds: float = 0.0,
                     compiles: int = 0, batches: int = 0) -> None:
        s = self._samples.setdefault(
            int(padded), {"rows": 0, "batches": 0, "seconds": 0.0,
                          "prep_seconds": 0.0, "compile_seconds": 0.0,
                          "compiles": 0})
        s["rows"] += int(b)
        s["batches"] += int(batches)
        s["seconds"] += float(seconds)
        s["prep_seconds"] += float(prep_seconds)
        s["compile_seconds"] += float(compile_seconds)
        s["compiles"] += int(compiles)

    def _flush_samples(self) -> None:
        """Emit the accumulated per-bucket samples as observations (called
        at drain time — the ``harvests at drain`` contract)."""
        if not self._samples or self.model_sig is None:
            self._samples.clear()
            return
        from ..tuning.observations import harvest_samples
        samples = [dict(bucket=k, **v)
                   for k, v in sorted(self._samples.items())]
        self._samples.clear()
        harvest_samples(
            self.model_sig, self.placement_key,
            {"mini_batch_size": self.mini_batch_size,
             "prefetch_depth": self.prefetch_depth,
             "buckets": None if self.buckets is None else list(self.buckets)},
            samples)

    # -- host side: coerce + pad (runs on the prefetch worker) ---------------
    def _prepare(self, sl: slice
                 ) -> Tuple[Dict[str, np.ndarray], int, int, float]:
        c = self.counters
        t_prep = time.perf_counter()
        with c.timer("coerce"), _span("runner.coerce"):
            feeds = self.coerce(sl)
        b = 0
        with c.timer("pad"), _span("runner.pad"):
            padded_feeds = {}
            padded = 0
            for name, arr in feeds.items():
                b = len(arr)
                padded = bucket_size(b, self.buckets)
                padded = -(-padded // self.shards) * self.shards
                if is_device_array(arr):
                    # device feed (resident column slice): pad on device,
                    # nothing crosses the bus
                    padded_feeds[name] = pad_axis_device(arr, padded)
                elif self.staging is not None:
                    buf = self.staging.acquire((padded,) + arr.shape[1:],
                                               arr.dtype)
                    buf[:b] = arr
                    if padded > b:
                        buf[b:] = 0
                    padded_feeds[name] = buf
                else:
                    padded_feeds[name] = pad_axis(arr, padded)
            _tracing.add_event("pad_bucket", rows=b, padded=padded)
        return padded_feeds, b, padded, time.perf_counter() - t_prep

    def _prepared_batches(self, n_rows: int):
        slices = batch_slices(n_rows, self.mini_batch_size)
        if self.prefetch_depth > 0 and len(slices) > 1:
            # batch k+1's coerce/pad overlaps batch k's h2d + dispatch; the
            # depth bound caps host memory at that many prepared batches.
            # The worker thread starts with an empty context — propagate()
            # carries the active trace + installed SpanTracer across, so
            # coerce/pad spans land in the request's trace
            prepare = _tracing.propagate(self._prepare)
            return PrefetchIterator((prepare(sl) for sl in slices),
                                    depth=self.prefetch_depth)
        return (self._prepare(sl) for sl in slices)

    # -- device side: feed, dispatch, overlapped drain -----------------------
    def run(self, n_rows: int) -> List[Tuple[dict, int]]:
        """Dispatch every minibatch; returns [(device outputs, valid rows)].

        JAX dispatch returns futures, so the loop never blocks on compute;
        each batch's outputs start their device→host copy immediately
        (``copy_to_host_async``) instead of at partition end.
        """
        c = self.counters
        if self.tuning == "auto" and not self._tuned:
            self._resolve_auto(n_rows)
        pending: List[Tuple[dict, int]] = []
        with _span("runner.run", rows=n_rows):
            batches = self._prepared_batches(n_rows)
            # prefetch_wait: time the dispatch thread blocks on the coerce/
            # pad worker — zero when host prep fully overlaps device work;
            # bench derives its h2d-overlap fraction from this vs coerce+pad
            prefetching = isinstance(batches, PrefetchIterator)
            it = iter(batches)
            while True:
                t0 = time.perf_counter()
                try:
                    feeds_host, b, padded, prep_s = next(it)
                except StopIteration:
                    break
                if prefetching:
                    c.add("prefetch_wait", time.perf_counter() - t0)
                device_fed = [k for k, v in feeds_host.items()
                              if is_device_array(v)]
                if device_fed:
                    record_hit(len(device_fed))
                nbytes = sum(a.nbytes for k, a in feeds_host.items()
                             if k not in device_fed)
                # cost attribution: bill this batch's padding waste and
                # feed bytes to the ambient trace's workload class
                _ledger_charge("padding_waste_rows", padded - b)
                _ledger_charge("h2d_bytes", nbytes)
                with c.timer("h2d", nbytes):
                    # put() is placement-aware; for an already-resident feed
                    # it is a same-device no-op (or an on-chip move), never
                    # a host round-trip
                    feeds = {k: self.put(v) for k, v in feeds_host.items()}
                before = jit_cache_size(self.jitted)
                t0 = time.perf_counter()
                outs = self.jitted(self.params, feeds)
                elapsed = time.perf_counter() - t0
                after = jit_cache_size(self.jitted)
                if before is not None and after is not None \
                        and after > before:
                    # the dispatch call blocked on trace+compile — a bucket
                    # the warm-up vocabulary missed; attribute the stall
                    # honestly
                    c.add("compile", elapsed, count=after - before)
                    _ledger_charge("compile_seconds", elapsed)
                    M_CACHE_MISSES.inc(after - before)
                    M_STEADY_RECOMPILES.inc(after - before)
                    _tracing.add_event("cache_miss", compiles=after - before,
                                       seconds=elapsed)
                    self._note_sample(padded, b, batches=1,
                                      prep_seconds=prep_s,
                                      compile_seconds=elapsed,
                                      compiles=after - before)
                else:
                    c.add("dispatch", elapsed)
                    _ledger_charge("device_seconds", elapsed)
                    M_CACHE_HITS.inc()
                    _tracing.add_event("cache_hit")
                    self._note_sample(padded, b, batches=1, seconds=elapsed,
                                      prep_seconds=prep_s)
                if self.staging is not None:
                    # a slab may only circulate once its async h2d has
                    # finished reading it: block on the *input* transfers
                    # (not the compute) before returning buffers to the pool
                    for k, v in feeds.items():
                        if k not in device_fed:
                            try:
                                # tpulint: disable=TPU001 — waits for the
                                # INPUT transfer (not compute): the slab is
                                # immutable-until-transfer-completes and may
                                # only recirculate after the copy lands
                                v.block_until_ready()
                            except Exception:
                                pass
                    for v in feeds_host.values():
                        self.staging.release(v)
                for v in outs.values():
                    try:
                        v.copy_to_host_async()
                    except Exception:
                        break  # backend without async copy; drain still works
                pending.append((outs, b))
        return pending

    def drain(self, pending: List[Tuple[dict, int]]
              ) -> List[Tuple[Dict[str, np.ndarray], int]]:
        """One batched device→host fetch over every pending output; flushes
        the per-bucket tuning samples accumulated since the last drain."""
        if not pending:
            self._flush_samples()
            return []
        t0 = time.perf_counter()
        # device_get is where a wedged device parks the dispatcher forever
        # — the watchdog turns that silent hang into a diagnostic bundle
        with _span("runner.d2h", batches=len(pending)), _watch("runner_drain"):
            host = jax.device_get([outs for outs, _ in pending])
        elapsed = time.perf_counter() - t0
        nbytes = sum(a.nbytes for outs in host for a in outs.values())
        self.counters.add("d2h", elapsed, nbytes)
        # async dispatch settles inside device_get, so the drain wall time
        # IS device time — ledger device_seconds reconciles with the
        # dispatch+d2h stage counters by construction
        _ledger_charge("device_seconds", elapsed)
        _ledger_charge("d2h_bytes", nbytes)
        # async dispatch means compute largely settles inside device_get:
        # attribute the drain across buckets by row share so the per-bucket
        # fit sees the true device cost, not just the enqueue time
        total_rows = sum(s["rows"] for s in self._samples.values()) or 1
        for s in self._samples.values():
            s["seconds"] += elapsed * (s["rows"] / total_rows)
        self._flush_samples()
        return [(outs, b) for outs, (_, b) in zip(host, pending)]

    def run_and_drain(self, n_rows: int
                      ) -> List[Tuple[Dict[str, np.ndarray], int]]:
        return self.drain(self.run(n_rows))
