"""JAX linear learners: logistic & linear regression.

These are the framework's built-in baseline learners — the role SparkML's
``LogisticRegression``/``LinearRegression`` play for the reference's
``TrainClassifier``/``TrainRegressor`` (``train/TrainClassifier.scala:50``
auto-fits any learner; its default model zoo is SparkML linear/tree models).

TPU-first design: full-batch training as one jitted ``lax.scan`` over Adam
steps — the whole optimization is a single XLA program, no per-step host
round-trips. The X·W matmul dominates and lands on the MXU.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import (ComplexParam, HasFeaturesCol, HasLabelCol,
                           HasPredictionCol, HasProbabilityCol, HasWeightCol,
                           Param)
from ..core.pipeline import Estimator, Model
from ..core.schema import assemble_vector

__all__ = ["LogisticRegression", "LogisticRegressionModel",
           "LinearRegression", "LinearRegressionModel"]


def _run_linear(Xd, yd, wd, params, reg, lr, n_out, loss_kind, steps):
    """Module-level jitted trainer: data/params are traced arguments so
    same-shape fits (e.g. TuneHyperparameters trials) hit the jit cache
    instead of re-compiling with the dataset baked in as constants."""
    import jax
    import jax.numpy as jnp
    import optax

    opt = optax.adam(lr)

    def loss_fn(p):
        logits = Xd @ p["W"] + p["b"]
        if loss_kind == "logistic":
            ll = optax.softmax_cross_entropy_with_integer_labels(
                logits, yd.astype(jnp.int32))
        else:
            ll = 0.5 * (logits[:, 0] - yd.astype(jnp.float32)) ** 2
        # SparkML parity: the intercept is not penalized
        return jnp.sum(ll * wd) / jnp.sum(wd) + reg * jnp.sum(p["W"] ** 2)

    state = opt.init(params)

    def step(carry, _):
        p, s = carry
        g = jax.grad(loss_fn)(p)
        updates, s = opt.update(g, s, p)
        return (optax.apply_updates(p, updates), s), None

    (p, _), _ = jax.lax.scan(step, (params, state), None, length=steps)
    return p


def _jitted_runner():
    import jax
    if _jitted_runner._cached is None:
        # reg/lr are traced scalars so hyperparameter sweeps share ONE
        # compilation; only shape-determining knobs are static
        _jitted_runner._cached = jax.jit(
            _run_linear, static_argnames=("n_out", "loss_kind", "steps"))
    return _jitted_runner._cached


_jitted_runner._cached = None


def _fit_linear(X: np.ndarray, y: np.ndarray, w: Optional[np.ndarray],
                n_out: int, loss_kind: str, reg: float, lr: float,
                steps: int, seed: int):
    """Run the jitted trainer; returns (W, b) as numpy."""
    import jax
    import jax.numpy as jnp

    Xd = jnp.asarray(X, dtype=jnp.float32)
    yd = jnp.asarray(y)
    wd = jnp.ones(len(X), jnp.float32) if w is None else jnp.asarray(w, jnp.float32)
    key = jax.random.PRNGKey(seed)
    params = {
        "W": jax.random.normal(key, (X.shape[1], n_out)) * 0.01,
        "b": jnp.zeros((n_out,)),
    }
    p = _jitted_runner()(Xd, yd, wd, params, jnp.float32(reg), jnp.float32(lr),
                         n_out=n_out, loss_kind=loss_kind, steps=steps)
    return np.asarray(p["W"]), np.asarray(p["b"])


class _LinearParams(HasFeaturesCol, HasLabelCol, HasWeightCol):
    reg_param = Param(float, default=0.0, doc="L2 regularization strength")
    max_iter = Param(int, default=200, doc="optimizer steps")
    learning_rate = Param(float, default=0.1, doc="Adam learning rate")
    seed = Param(int, default=0, doc="init seed")


class LogisticRegression(Estimator, _LinearParams, HasPredictionCol,
                         HasProbabilityCol):
    """Multiclass logistic regression (softmax), full-batch on device."""

    def _fit(self, df: DataFrame) -> "LogisticRegressionModel":
        X = assemble_vector(df, [self.get("features_col")])
        y_raw = df[self.get("label_col")]
        classes, y = np.unique(y_raw, return_inverse=True)
        wcol = self.get_or_none("weight_col")
        w = df[wcol].astype(np.float64) if wcol else None
        W, b = _fit_linear(X, y, w, len(classes), "logistic",
                           self.get("reg_param"), self.get("learning_rate"),
                           self.get("max_iter"), self.get("seed"))
        m = LogisticRegressionModel()
        m.set(features_col=self.get("features_col"),
              prediction_col=self.get("prediction_col"),
              probability_col=self.get("probability_col"),
              coefficients=W, intercept=b,
              classes=[c.item() if isinstance(c, np.generic) else c
                       for c in classes])
        return m


class LogisticRegressionModel(Model, HasFeaturesCol, HasPredictionCol,
                              HasProbabilityCol):
    coefficients = ComplexParam(default=None, doc="(d, k) weight matrix")
    intercept = ComplexParam(default=None, doc="(k,) bias")
    classes = Param(list, default=[], doc="class values by column index")

    def _transform(self, df: DataFrame) -> DataFrame:
        import jax.numpy as jnp
        from jax.nn import softmax
        X = assemble_vector(df, [self.get("features_col")])
        logits = jnp.asarray(X, jnp.float32) @ jnp.asarray(
            self.get("coefficients")) + jnp.asarray(self.get("intercept"))
        probs = np.asarray(softmax(logits, axis=-1))
        pred_idx = probs.argmax(axis=1)
        classes = np.asarray(self.get("classes"))
        prob_col = np.empty(len(X), dtype=object)
        for i in range(len(X)):
            prob_col[i] = probs[i]
        from ..core.schema import set_label_metadata
        out = (df.with_column(self.get("prediction_col"), classes[pred_idx])
                 .with_column(self.get("probability_col"), prob_col))
        # class order travels with the frame so metrics index probabilities
        # correctly even when the eval labels are a subset
        return set_label_metadata(out, self.get("prediction_col"),
                                  num_classes=len(classes),
                                  classes=self.get("classes"))


class LinearRegression(Estimator, _LinearParams, HasPredictionCol):
    def _fit(self, df: DataFrame) -> "LinearRegressionModel":
        X = assemble_vector(df, [self.get("features_col")])
        y = df[self.get("label_col")].astype(np.float64)
        wcol = self.get_or_none("weight_col")
        w = df[wcol].astype(np.float64) if wcol else None
        W, b = _fit_linear(X, y, w, 1, "squared",
                           self.get("reg_param"), self.get("learning_rate"),
                           self.get("max_iter"), self.get("seed"))
        m = LinearRegressionModel()
        m.set(features_col=self.get("features_col"),
              prediction_col=self.get("prediction_col"),
              coefficients=W, intercept=b)
        return m


class LinearRegressionModel(Model, HasFeaturesCol, HasPredictionCol):
    coefficients = ComplexParam(default=None, doc="(d, 1) weights")
    intercept = ComplexParam(default=None, doc="(1,) bias")

    def _transform(self, df: DataFrame) -> DataFrame:
        X = assemble_vector(df, [self.get("features_col")])
        pred = X @ np.asarray(self.get("coefficients"))[:, 0] \
            + np.asarray(self.get("intercept"))[0]
        return df.with_column(self.get("prediction_col"), pred)
