"""JaxModel — run any jittable callable as a pipeline stage.

The reference ships *two* deep-learning graph runners with one shape:
``ONNXModel`` and ``CNTKModel`` (``deep-learning/.../cntk/CNTKModel.scala:250-330``
— feed/fetch dict API, input coercion ``:387-434``, broadcast +
``mapPartitions`` evaluate). This framework deliberately subsumes the CNTK
path: legacy CNTK graphs convert to ONNX and run through :class:`ONNXModel`;
**new** models are native JAX functions — and this stage is their runner,
the generic non-ONNX model path.

Anything of the form ``apply(params, feeds) -> outputs`` is a model here:
a hand-written function, a flax/haiku ``Module.apply``, a zoo network. The
stage gives it the full DataFrame treatment the reference gives CNTK graphs:
minibatching, dtype management (bf16 on TPU), per-partition device pinning,
pipelined async dispatch, save/load (params as an npz pytree; the callable
by import path when it is a module-level function — the moral of
``CNTKFunctionParam``'s model-file reference).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, Param
from ..core.pipeline import Model
from ..ops.compile_cache import StageCounters, warm_up_model
from ..parallel.mesh import feed_placement
from .runner import BatchRunner, StagingSlabPool

__all__ = ["JaxModel"]


class JaxModel(Model):
    """Run ``apply_fn(params, {feed: array}) -> {name: array} | array``
    over DataFrame columns in device minibatches."""

    apply_fn = ComplexParam(default=None,
                            doc="callable (params, feeds) -> outputs; "
                                "module-level functions survive save/load "
                                "by import path, closures are transient")
    model_params = ComplexParam(default=None,
                                doc="pytree of arrays passed as first arg")
    feed_dict = Param(dict, default={}, doc="{feed name: dataframe column}; "
                                            "empty = first column as 'input'")
    fetch_dict = Param(dict, default={}, doc="{output column: output name}; "
                                             "empty = every output under its "
                                             "own name")
    mini_batch_size = Param(int, default=64, doc="rows per device batch")
    compute_dtype = Param(str, default="float32",
                          doc="float feeds/params cast to this on device "
                              "(bfloat16 recommended on TPU)")
    pin_devices = Param(bool, default=True,
                        doc="round-robin partitions over local chips")
    mesh_sharded = Param(bool, default=False,
                         doc="SPMD inference over the default mesh's first "
                             "axis (batch sharded, params replicated); "
                             "overrides pin_devices — see ONNXModel")
    prefetch_depth = Param(int, default=2,
                           doc="prepared batches coerced/padded ahead on a "
                               "background worker while the current batch "
                               "dispatches; bounds host memory at that many "
                               "padded batches. 0 = prepare inline on the "
                               "dispatch thread")
    buckets = Param((list, int), default=[],
                    doc="custom padding-bucket ladder (sorted batch sizes); "
                        "empty = next-power-of-two. Warm-up and the runner "
                        "derive every padded shape through the same ladder")
    tuning = Param(str, default="", choices=["", "auto"],
                   doc="'auto' consults the measurement-driven tuning store "
                       "(MMLSPARK_TPU_TUNING_DIR): the fitted cost model "
                       "picks mini_batch_size, prefetch_depth and the "
                       "bucket ladder; a cold store keeps the defaults")

    def __init__(self, apply_fn: Optional[Callable] = None,
                 model_params=None, **kw):
        super().__init__(**kw)
        if apply_fn is not None:
            self.set(apply_fn=apply_fn)
        if model_params is not None:
            self.set(model_params=model_params)
        self._jitted = None
        self._device_params: Dict[Optional[int], object] = {}
        self._params_lock = threading.Lock()
        self._counters = StageCounters()
        self._staging = StagingSlabPool()
        self._tuning_decisions: Dict[tuple, object] = {}

    @property
    def stage_counters(self) -> StageCounters:
        """coerce/pad/h2d/compile/dispatch/d2h instrumentation, cumulative
        over every transform/warm_up on this instance."""
        return self._counters

    def set(self, **kwargs):
        # any reconfiguration invalidates the compiled program and the
        # cached device-resident params (mirrors ONNXModel's _jit_sig)
        out = super().set(**kwargs)
        if kwargs and hasattr(self, "_params_lock"):
            self._jitted = None
            # under the lock like ONNXModel.set: a _params_for_device call
            # racing the reset must see either the old cache or the empty
            # one, never a dict it is mid-populating
            with self._params_lock:
                self._device_params = {}
        if kwargs and getattr(self, "_tuning_decisions", None) is not None:
            self._tuning_decisions.clear()
        return out

    # -- tuning --------------------------------------------------------------
    def tuning_signature(self) -> str:
        """Stable identity for the observation store: the apply_fn's import
        path (the callable IS the model) plus the compute dtype."""
        fn = self.get_or_none("apply_fn")
        name = (f"{getattr(fn, '__module__', '?')}."
                f"{getattr(fn, '__qualname__', repr(fn))}" if fn is not None
                else "unset")
        return f"jax:{name}:{self.compute_dtype}"

    def _mesh_shape(self) -> str:
        """Topology stamp for tuning decisions: the default mesh's
        canonical shape string when this model dispatches mesh-sharded,
        else ``"single"`` — decisions learned on one chip layout never
        seed another (their cost surfaces differ by ICI collectives)."""
        from ..parallel.mesh import get_default_mesh, mesh_shape
        if not self.get("mesh_sharded"):
            return "single"
        return mesh_shape(get_default_mesh())

    def _resolve_tuning(self, histogram: Dict[int, int]):
        """The store's pick for this histogram (None = off or cold store);
        resolved sig-wide so warm-up and every partition share one ladder.
        Decisions are keyed (and the store filtered) by mesh shape too, so
        toggling ``mesh_sharded`` mid-life never reuses a stale ladder."""
        if self.get_or_none("tuning") != "auto":
            return None
        mesh = self._mesh_shape()
        key = (tuple(sorted(histogram.items())), mesh)
        if key not in self._tuning_decisions:
            from ..tuning.cost_model import resolve_tuning
            self._tuning_decisions[key] = resolve_tuning(
                self.tuning_signature(), "default", histogram,
                defaults=(self.mini_batch_size, self.prefetch_depth),
                mesh_shape=mesh)
        return self._tuning_decisions[key]

    def _runner_config(self, n_rows: int):
        ladder = tuple(self.buckets) if self.get_or_none("buckets") else None
        decision = self._resolve_tuning({int(n_rows): 1})
        if decision is None:
            return self.mini_batch_size, self.prefetch_depth, ladder
        return (decision.mini_batch_size, decision.prefetch_depth,
                decision.buckets)

    # -- jit ----------------------------------------------------------------
    def _ensure_jitted(self):
        if self._jitted is None:
            fn = self.apply_fn
            if fn is None:
                raise ValueError(
                    f"{self.uid}: apply_fn is unset (a closure param does "
                    f"not survive save/load; re-set it after loading)")
            compute_dt = jnp.dtype(self.compute_dtype)
            fetch = dict(self.fetch_dict)

            def run(params, feeds):
                feeds = {k: (v.astype(compute_dt)
                             if jnp.issubdtype(v.dtype, jnp.floating)
                             and v.dtype != compute_dt else v)
                         for k, v in feeds.items()}
                out = fn(params, feeds)
                if not isinstance(out, dict):
                    out = {"output": out}
                if fetch:
                    return {col: out[name] for col, name in fetch.items()}
                return out

            self._jitted = jax.jit(run)
        return self._jitted

    def _cast_tree(self, params):
        """Float leaves → compute_dtype, on whatever devices hold them."""
        if self.compute_dtype == "float32" or params is None:
            return params
        dt = jnp.dtype(self.compute_dtype)
        cast = jax.jit(lambda p: jax.tree_util.tree_map(
            lambda v: (v.astype(dt)
                       if jnp.issubdtype(v.dtype, jnp.floating)
                       else v), p))
        return cast(params)

    def _params_for_device(self, device):
        key = id(device) if device is not None else None
        with self._params_lock:
            if key not in self._device_params:
                params = self.get_or_none("model_params")
                # f32 over the wire, compute_dtype cast on device (narrow
                # host buffers hit a slow transfer path; see ONNXModel).
                # staging stays under the lock on purpose: first touch per
                # device must be single-flight — two racing threads would
                # both device_put the full param tree (duplicate HBM +
                # link traffic); steady state is a dict hit
                self._device_params[key] = self._cast_tree(
                    jax.device_put(params, device)  # tpulint: disable=TPU014
                    if device is not None
                    else jax.device_put(params))    # tpulint: disable=TPU014
            return self._device_params[key]

    def _params_for_mesh(self, mesh):
        from ..parallel.mesh import replicated_sharding
        key = ("mesh", mesh)
        with self._params_lock:
            if key not in self._device_params:
                # single-flight staging, as in _params_for_device
                self._device_params[key] = self._cast_tree(jax.device_put(  # tpulint: disable=TPU014
                    self.get_or_none("model_params"),
                    replicated_sharding(mesh)))
            return self._device_params[key]

    # -- execution ----------------------------------------------------------
    @staticmethod
    def _coerce_col(col: np.ndarray) -> np.ndarray:
        if col.dtype == object:
            col = np.stack([np.asarray(v) for v in col])
        arr = np.asarray(col)
        if arr.dtype == np.float64:
            arr = arr.astype(np.float32)
        return arr

    def _placement_params(self, pidx: int):
        placement = feed_placement(
            self.get("mesh_sharded"), pidx, self.pin_devices)
        params = (self._params_for_mesh(placement.mesh)
                  if placement.mesh is not None
                  else self._params_for_device(placement.device))
        return placement, params

    def _run_batches(self, part: DataFrame, pidx: int) -> DataFrame:
        """One partition through the shared feed/drain pipeline (see
        :class:`~mmlspark_tpu.models.runner.BatchRunner` — prefetch, async
        h2d, overlapped d2h drain; the same machinery as ONNXModel)."""
        jitted = self._ensure_jitted()
        feed = dict(self.feed_dict) or {"input": part.columns[0]}
        placement, params = self._placement_params(pidx)

        # resident input columns feed device slices (no host coercion,
        # zero h2d payload; BatchRunner counts the residency hits)
        resident = {col_name: part.device_column(col_name).device_array()
                    for col_name in feed.values()
                    if part.is_resident(col_name)}

        def coerce(sl: slice) -> Dict[str, np.ndarray]:
            out = {}
            for feed_name, col_name in feed.items():
                dev = resident.get(col_name)
                out[feed_name] = dev[sl] if dev is not None \
                    else self._coerce_col(part[col_name][sl])
            return out

        mbs, depth, ladder = self._runner_config(len(part))
        runner = BatchRunner(jitted, params, coerce, placement.put,
                             shards=placement.shards,
                             mini_batch_size=mbs,
                             prefetch_depth=depth,
                             counters=self._counters,
                             staging=self._staging,
                             buckets=ladder,
                             model_sig=self.tuning_signature(),
                             placement_key=str(placement.key))
        pending = runner.run_and_drain(len(part))

        if not pending:
            return part
        out_cols = list(pending[0][0])
        out = part
        for col_name in out_cols:
            chunks = [outs[col_name][:b] for outs, b in pending]
            arr = np.concatenate(chunks)
            if arr.dtype == jnp.bfloat16:
                arr = arr.astype(np.float32)
            out = out.with_column(col_name, arr)
        return out

    # -- AOT warm-up ---------------------------------------------------------
    def warm_up(self, input_specs: Dict[str, tuple],
                batch_sizes: Optional[List[int]] = None,
                background: bool = False):
        """Compile every padding-bucket shape ahead of first traffic.

        ``apply_fn`` is opaque (no graph metadata to introspect), so
        ``input_specs`` is required: {feed name: (dtype, per-row shape)}.
        Otherwise identical to :meth:`ONNXModel.warm_up` — one zero batch
        per bucket per placement, populating the jit cache (and the
        persistent compilation cache when enabled).
        """
        jitted = self._ensure_jitted()
        specs = {name: (np.dtype(dt), tuple(shape))
                 for name, (dt, shape) in input_specs.items()}
        sizes = [int(b) for b in (batch_sizes or [self.mini_batch_size])]
        ladder = tuple(self.buckets) if self.get_or_none("buckets") else None
        decision = self._resolve_tuning({s: 1 for s in sizes})
        if decision is not None:
            sizes = list(decision.warm_up_sizes) or sizes
            ladder = decision.buckets
        return warm_up_model(self, jitted, specs, sizes,
                             background=background, buckets=ladder)

    def _transform(self, df: DataFrame) -> DataFrame:
        self._ensure_jitted()
        return df.map_partitions(self._run_batches)

    # -- persistence --------------------------------------------------------
    def _load_extra(self, path: str) -> None:
        self._jitted = None
        # load-time rebuild of a just-deserialized instance: the lock
        # itself is recreated on the next line, so nothing can hold it
        # tpulint: disable=TPU012
        self._device_params = {}
        self._params_lock = threading.Lock()
        self._counters = StageCounters()
        self._staging = StagingSlabPool()
        self._tuning_decisions = {}
