from .binning import BinMapper
from .booster import Booster
from .estimators import (LightGBMClassificationModel, LightGBMClassifier,
                         LightGBMRanker, LightGBMRankerModel,
                         LightGBMRegressionModel, LightGBMRegressor)
from .train import train

__all__ = ["BinMapper", "Booster", "train", "LightGBMClassifier",
           "LightGBMClassificationModel", "LightGBMRegressor",
           "LightGBMRegressionModel", "LightGBMRanker", "LightGBMRankerModel"]
