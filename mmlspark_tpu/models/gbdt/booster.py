"""Booster — fitted GBDT model container.

Parity surface: ``LightGBMBooster``
(``lightgbm/.../booster/LightGBMBooster.scala``): score/raw score
(``score:390-401``), leaf prediction (``predictLeaf:403-412``), TreeSHAP
feature contributions (``featuresShap:414-423``), save/load model string
(``saveToString:269-274``), booster merging for batch warm-start
(``mergeBooster:252-256``), feature importances (``:491-498``).

Trees live as stacked fixed-shape arrays (T, …) so prediction is one
``lax.scan`` over trees of vectorized gathers — no per-node pointer chasing.
TreeSHAP is the polynomial-time path-dependent algorithm, vectorized over
samples (the recursion visits tree nodes; per-sample state is only the
one_fraction vector), using training-set covers stored at fit time.
"""

from __future__ import annotations

import base64
import io
import json
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .trees import predict_leaf_indices, predict_trees_any

__all__ = ["Booster"]


class Booster:
    def __init__(self, depth: int, n_features: int, objective: str,
                 base_score: float = 0.0, num_class: int = 1,
                 feats: Optional[np.ndarray] = None,
                 thr_raw: Optional[np.ndarray] = None,
                 leaf_values: Optional[np.ndarray] = None,
                 gains: Optional[np.ndarray] = None,
                 covers: Optional[np.ndarray] = None,
                 best_iteration: int = -1):
        self.depth = depth
        self.n_features = n_features
        self.objective = objective
        self.base_score = base_score
        self.num_class = num_class
        n_int = 2 ** depth - 1
        n_leaf = 2 ** depth
        n_all = 2 ** (depth + 1) - 1
        shape_leaf = (0, num_class, n_leaf) if num_class > 1 else (0, n_leaf)
        # tree arrays accumulate in a pending list (appending per boosting
        # iteration must be O(1), not a full re-concatenation) and are stacked
        # lazily behind cached properties
        self._base = {
            "feats": feats if feats is not None else np.zeros((0, n_int), np.int32),
            "thr_raw": thr_raw if thr_raw is not None else np.zeros((0, n_int), np.float32),
            "leaf_values": leaf_values if leaf_values is not None else
                np.zeros(shape_leaf, np.float32),
            "gains": gains if gains is not None else np.zeros((0, n_int), np.float32),
            "covers": covers if covers is not None else np.zeros((0, n_all), np.float32),
        }
        self._pending: List[tuple] = []
        self.best_iteration = best_iteration
        #: label-ordered categorical encoder (categorical.py); applied to
        #: raw X before every prediction path when set
        self.cat_encoder = None
        #: training hyperparams refit() needs on the same scale
        #: (learning_rate, lambda_l2); stamped by train(), serialized
        self.fit_params = None
        #: linear trees (LightGBM linear_tree): per-leaf ridge coefficients
        #: over the leaf's path features — {"coefs": (T, 2^D, D+1),
        #: "pf": (T, 2^D, D)} or None for constant-leaf models. When set,
        #: prediction evaluates the leaf's linear model; leaf_values hold
        #: the constant fallback (the coefs' bias) for introspection only.
        self._lin_base = None
        self._lin_pending: List[tuple] = []

    # -- bookkeeping --------------------------------------------------------
    _FIELDS = ("feats", "thr_raw", "leaf_values", "gains", "covers")

    def _materialize(self) -> None:
        if self._pending:
            for i, name in enumerate(self._FIELDS):
                self._base[name] = np.concatenate(
                    [self._base[name]] + [np.asarray(p[i])[None]
                                          for p in self._pending])
            self._pending = []
        if self._lin_pending:
            parts = {
                "coefs": [np.asarray(p[0])[None] for p in self._lin_pending],
                "pf": [np.asarray(p[1])[None] for p in self._lin_pending],
            }
            if self._lin_base is None:
                self._lin_base = {k: np.concatenate(v)
                                  for k, v in parts.items()}
            else:
                self._lin_base = {
                    k: np.concatenate([self._lin_base[k]] + parts[k])
                    for k in parts}
            self._lin_pending = []

    def __getattr__(self, name):
        if name in Booster._FIELDS:
            self._materialize()
            return self._base[name]
        raise AttributeError(name)

    @property
    def is_linear(self) -> bool:
        """True for linear-leaf models (LightGBM ``linear_tree``)."""
        return self._lin_base is not None or bool(self._lin_pending)

    @property
    def linear(self) -> Optional[Dict]:
        self._materialize()
        return self._lin_base

    @property
    def num_trees(self) -> int:
        return len(self._base["feats"]) + len(self._pending)

    def append_tree(self, feat, thr_raw, leaf_value, gain, cover,
                    coefs=None, pf=None):
        if (coefs is None) != (pf is None) \
                or ((coefs is None) and self.is_linear) \
                or (coefs is not None and self.num_trees and not self.is_linear):
            raise ValueError("a booster is linear for all trees or none")
        self._pending.append((feat, thr_raw, leaf_value, gain, cover))
        if coefs is not None:
            self._lin_pending.append((coefs, pf))

    def scale_trees(self, idx, factor: float) -> None:
        """Multiply the leaf outputs of trees ``idx`` in place (DART's
        k/(k+1) re-weighting of dropped trees). Linear leaves scale their
        whole coefficient vector — the output is linear in it."""
        self._materialize()
        lv = self._base["leaf_values"]
        lv[np.asarray(idx, dtype=np.int64)] *= np.float32(factor)
        if self._lin_base is not None:
            self._lin_base["coefs"][np.asarray(idx, dtype=np.int64)] *= \
                np.float32(factor)

    def truncated(self, n_trees: int) -> "Booster":
        """Model truncated to the first n_trees (early-stopping cutoff).

        Arrays are copied, not viewed: dart's ``scale_trees`` mutates leaf
        values in place, and a snapshot that aliased the live stack would
        silently drift."""
        b = Booster(self.depth, self.n_features, self.objective,
                    self.base_score, self.num_class,
                    self.feats[:n_trees].copy(), self.thr_raw[:n_trees].copy(),
                    self.leaf_values[:n_trees].copy(),
                    self.gains[:n_trees].copy(),
                    self.covers[:n_trees].copy(), best_iteration=n_trees)
        b.cat_encoder = self.cat_encoder  # trees split in the encoded space
        b.fit_params = self.fit_params
        if self.is_linear:
            lin = self.linear
            b._lin_base = {k: lin[k][:n_trees].copy() for k in lin}
        return b

    def merge(self, other: "Booster") -> "Booster":
        """Concatenate trees (parity: mergeBooster for numBatches training)."""
        assert self.depth == other.depth and self.num_class == other.num_class
        if self.is_linear != other.is_linear:
            raise ValueError("cannot merge a linear-tree booster with a "
                             "constant-leaf booster")
        merged = Booster(
            self.depth, self.n_features, self.objective, self.base_score,
            self.num_class,
            np.concatenate([self.feats, other.feats]),
            np.concatenate([self.thr_raw, other.thr_raw]),
            np.concatenate([self.leaf_values, other.leaf_values]),
            np.concatenate([self.gains, other.gains]),
            np.concatenate([self.covers, other.covers]))
        merged.cat_encoder = self.cat_encoder
        merged.fit_params = self.fit_params
        if self.is_linear:
            a, b = self.linear, other.linear
            merged._lin_base = {k: np.concatenate([a[k], b[k]]) for k in a}
        return merged

    # -- prediction ---------------------------------------------------------
    # NOTE: thresholds and feature comparisons are float32 end-to-end (the
    # TPU-native layout; f64 is emulated on TPU). Features needing exact
    # splits must be distinguishable in float32 (|x| < 2^23 for integer ids, so bin-midpoint
    # thresholds stay representable)
    # — a deliberate deviation from LightGBM's double-precision thresholds.
    def _x_eff(self, X: np.ndarray):
        """Raw matrix → the space the trees split in (categorical columns
        replaced by their label-ordered ranks). scipy-sparse X passes
        through untouched (predict densifies it in bounded chunks)."""
        from .binning import is_sparse
        if is_sparse(X):
            if self.cat_encoder is not None:
                raise ValueError("categorical encoding and sparse features "
                                 "cannot combine (rank-encode before "
                                 "sparsifying, or pass dense input)")
            return X
        if self.cat_encoder is not None:
            X = self.cat_encoder.transform(np.asarray(X))
        return np.asarray(X, dtype=np.float32)

    def _tree_cap(self, num_iteration: Optional[int]) -> int:
        """Trees used for a ``num_iteration`` predict cap (LightGBM
        semantics: None = the early-stopped ``best_iteration`` when one
        exists, else all; <= 0 = all; multiclass counts ITERATIONS, each
        num_class trees). ``best_iteration`` is ABSOLUTE (warm-start init
        iterations included)."""
        if num_iteration is None:
            it = self.best_iteration if self.best_iteration > 0 else 0
        elif num_iteration <= 0:
            it = 0
        else:
            it = int(num_iteration)
        if not it:
            return self.num_trees
        k = self.num_class if self.num_class > 1 else 1
        return min(self.num_trees, it * k)

    def raw_score(self, X: np.ndarray,
                  num_iteration: Optional[int] = None) -> np.ndarray:
        X = self._x_eff(X)
        T = self._tree_cap(num_iteration)
        if T == 0:
            shape = (X.shape[0], self.num_class) if self.num_class > 1 \
                else (X.shape[0],)
            return np.full(shape, self.base_score, dtype=np.float32)
        if self.is_linear:
            lin = self.linear
            if self.num_class > 1:
                from .trees import predict_trees_linear_multi_any
                out = predict_trees_linear_multi_any(
                    self.feats[:T], self.thr_raw[:T], lin["coefs"][:T],
                    lin["pf"][:T], X, depth=self.depth,
                    num_class=self.num_class)
            else:
                from .trees import predict_trees_linear_any
                out = predict_trees_linear_any(
                    self.feats[:T], self.thr_raw[:T], lin["coefs"][:T],
                    lin["pf"][:T], X, depth=self.depth)
        else:
            out = predict_trees_any(self.feats[:T], self.thr_raw[:T],
                                    self.leaf_values[:T], X, depth=self.depth)
        return np.asarray(out) + self.base_score

    def predict(self, X: np.ndarray, raw_score: bool = False,
                num_iteration: Optional[int] = None) -> np.ndarray:
        """``num_iteration``: predict with the first k iterations only
        (LightGBM's knob; -1 = the early-stopped best_iteration)."""
        raw = self.raw_score(X, num_iteration=num_iteration)
        if raw_score:
            return raw
        from .objectives import get_objective
        obj = get_objective(self.objective, num_class=max(self.num_class, 2))
        return np.asarray(obj.transform(raw))

    def predict_leaf(self, X: np.ndarray) -> np.ndarray:
        X = self._x_eff(X)
        from .trees import apply_chunked_dense
        return apply_chunked_dense(
            lambda xd: predict_leaf_indices(self.feats, self.thr_raw, xd,
                                            depth=self.depth),
            X, empty_shape=(0, self.num_trees), empty_dtype=np.int32)

    # -- TreeSHAP -----------------------------------------------------------
    def shap_values(self, X: np.ndarray) -> np.ndarray:
        """Path-dependent TreeSHAP. Returns (n, F+1): per-feature
        contributions plus the expected value in the last column (the layout
        LightGBM's predict_contrib emits)."""
        from .treeshap import tree_shap
        from .binning import is_sparse
        if self.is_linear:
            raise NotImplementedError(
                "TreeSHAP over linear leaves is not defined (LightGBM "
                "rejects predict_contrib for linear_tree models too)")
        X = self._x_eff(X)
        if is_sparse(X):
            # the SHAP recursion walks every tree per row anyway — densify
            # in chunks so peak memory stays O(chunk × F)
            from .trees import apply_chunked_dense
            width = (self.num_class, 0, self.n_features + 1) \
                if self.num_class > 1 else (0, self.n_features + 1)
            return apply_chunked_dense(self.shap_values, X,
                                       empty_shape=width, chunk=1 << 14,
                                       concat_axis=-2)
        n = len(X)
        K = self.num_class if self.num_class > 1 else 1
        phi = np.zeros((K, n, self.n_features + 1), dtype=np.float64)
        for t in range(self.num_trees):
            lv = self.leaf_values[t]
            for k in range(K):
                tree_shap(self.feats[t], self.thr_raw[t],
                          lv[k] if self.num_class > 1 else lv,
                          self.covers[t], self.depth, X, phi[k])
        phi[:, :, -1] += self.base_score
        out = phi if self.num_class > 1 else phi[0]
        return out.astype(np.float32)

    # -- refit (parity: LightGBM Booster.refit) -----------------------------
    def refit(self, X, y, decay_rate: float = 0.9,
              learning_rate: Optional[float] = None,
              lam: Optional[float] = None,
              sample_weight=None) -> "Booster":
        """Adapt the model to NEW data without changing tree structures:
        every tree keeps its splits, leaf values are re-estimated on
        ``(X, y)`` and blended ``decay*old + (1-decay)*new`` — LightGBM's
        ``Booster.refit(decay_rate=0.9)``, the cheap domain-shift
        adaptation between full retrains.

        Trees refit sequentially in boosting order (each tree's gradients
        are taken at the running scores of the already-refitted prefix),
        matching the additive-model semantics of the original fit.
        ``learning_rate``/``lam`` default to the TRAINING values stamped on
        the booster (LightGBM reuses the model's own shrinkage; estimates
        on a different scale would drift toward base_score).
        """
        if self.is_linear:
            raise NotImplementedError(
                "refit re-estimates constant leaf values; linear leaves "
                "need a full linear refit (retrain instead)")
        if not 0.0 <= decay_rate <= 1.0:
            raise ValueError(f"decay_rate must be in [0, 1], got {decay_rate}")
        fp = getattr(self, "fit_params", None) or {}
        if learning_rate is None:
            learning_rate = float(fp.get("learning_rate", 0.1))
        if lam is None:
            lam = float(fp.get("lambda_l2", 0.0)) + 1e-10  # train's lam
        from .objectives import get_objective
        y = np.asarray(y, dtype=np.float64)
        w = (np.asarray(sample_weight, dtype=np.float64)
             if sample_weight is not None else np.ones(len(y)))
        K = self.num_class if self.num_class > 1 else 1
        obj = get_objective(self.objective, num_class=max(K, 2))
        # leaf index per (row, tree) in one pass (predict_leaf applies the
        # categorical encoding itself); per-tree leaf sums after. Multiclass
        # trees share one structure with K leaf-value sets, so the same
        # (n, T) index table serves every class.
        leaves = np.asarray(self.predict_leaf(X))              # (n, T)
        n_leaf = 2 ** self.depth
        new_lv = np.array(self.leaf_values, dtype=np.float32, copy=True)
        scores = jnp.full((len(y), K) if K > 1 else len(y),
                          self.base_score, jnp.float32)
        if obj.grad_hess is None:
            raise NotImplementedError(
                f"refit needs analytic gradients for {self.objective!r}")
        grad_fn = jax.jit(obj.grad_hess)
        yd, wd = jnp.asarray(y), jnp.asarray(w)
        # one code path for both arities: view leaf values as (T, K, L)
        # (contiguous copy, so the reshape is a writable view) and
        # gradients as (n, K); binary is the K == 1 degenerate case
        lvv = new_lv.reshape(self.num_trees, K, n_leaf)
        for t in range(self.num_trees):
            g, h = grad_fn(scores, yd, wd)
            g = np.asarray(g, dtype=np.float64).reshape(len(y), K)
            h = np.asarray(h, dtype=np.float64).reshape(len(y), K)
            li = leaves[:, t]
            # tree t was trained for class t % K only (class-major append
            # order, the same invariant prediction routes by);
            # re-estimating the other class rows would blend toward zeros
            # that were never trained estimates and give every tree K
            # times its trained per-class influence
            k = t % K
            Gs = np.bincount(li, weights=g[:, k], minlength=n_leaf)
            Hs = np.bincount(li, weights=h[:, k], minlength=n_leaf)
            opt = np.where(Hs > 0,
                           -Gs / (Hs + lam) * learning_rate, 0.0)
            blended = (decay_rate * lvv[t, k]
                       + (1.0 - decay_rate) * opt).astype(np.float32)
            # empty leaves keep their trained value (no evidence to move)
            lvv[t, k] = np.where(Hs > 0, blended, lvv[t, k])
            upd = jnp.asarray(lvv[t].T, jnp.float32)[li]   # (n, K)
            scores = scores + (upd if K > 1 else upd[:, 0])
        out = Booster(self.depth, self.n_features, self.objective,
                      self.base_score, self.num_class,
                      self.feats.copy(), self.thr_raw.copy(), new_lv,
                      self.gains.copy(), self.covers.copy(),
                      best_iteration=self.best_iteration)
        out.cat_encoder = self.cat_encoder
        out.fit_params = self.fit_params
        return out

    # -- introspection (parity: LightGBM Booster.trees_to_dataframe) --------
    def trees_to_dataframe(self):
        """Flatten the model into a row-per-node DataFrame: tree_index,
        node_index (heap order), node_type (split/stub/leaf),
        split_feature (-1 for stubs/leaves), threshold, split_gain,
        count (training cover), class_index (-1 for structure rows),
        value (per-class leaf outputs — multiclass emits one leaf row per
        class). The debugging/analysis surface LightGBM exposes under the
        same name; fully vectorized (a large model flattens in ms)."""
        from ...core.dataframe import DataFrame
        T = self.num_trees
        n_leaf = 2 ** self.depth
        n_int = n_leaf - 1
        K = self.num_class if self.num_class > 1 else 1
        feats = np.asarray(self.feats).reshape(T, n_int)
        stub = feats.ravel() < 0
        nan_if = lambda a: np.where(stub, np.nan, a)        # noqa: E731
        internal = {
            "tree_index": np.repeat(np.arange(T), n_int),
            "node_index": np.tile(np.arange(n_int), T),
            "node_type": np.where(stub, "stub", "split"),
            "split_feature": feats.ravel(),
            "threshold": nan_if(np.asarray(self.thr_raw).ravel()
                                .astype(np.float64)),
            "split_gain": nan_if(np.asarray(self.gains).ravel()
                                 .astype(np.float64)),
            "count": np.asarray(self.covers)[:, :n_int].ravel()
                     .astype(np.float64),
            "class_index": np.full(T * n_int, -1),
            "value": np.full(T * n_int, np.nan),
        }
        lv = np.asarray(self.leaf_values).reshape(T, K, n_leaf)
        leaf_cov = np.asarray(self.covers)[:, n_int:].astype(np.float64)
        leaf = {
            "tree_index": np.repeat(np.arange(T), K * n_leaf),
            "node_index": np.tile(np.arange(n_int, n_int + n_leaf), T * K),
            "node_type": np.full(T * K * n_leaf, "leaf"),
            "split_feature": np.full(T * K * n_leaf, -1),
            "threshold": np.full(T * K * n_leaf, np.nan),
            "split_gain": np.full(T * K * n_leaf, np.nan),
            "count": np.repeat(leaf_cov[:, None, :], K, axis=1).ravel(),
            "class_index": np.tile(np.repeat(np.arange(K), n_leaf), T)
                           if K > 1 else np.zeros(T * n_leaf, np.int64),
            "value": lv.astype(np.float64).ravel(),
        }
        return DataFrame({k: np.concatenate([internal[k],
                                             np.asarray(leaf[k])])
                          for k in internal})

    # -- importances --------------------------------------------------------
    def feature_importance(self, importance_type: str = "split") -> np.ndarray:
        imp = np.zeros(self.n_features)
        valid = self.feats >= 0
        if importance_type == "split":
            np.add.at(imp, self.feats[valid], 1)
        elif importance_type == "gain":
            np.add.at(imp, self.feats[valid], self.gains[valid])
        else:
            raise ValueError(f"importance_type {importance_type!r}")
        return imp

    # -- persistence (parity: saveToString / loadFromString) ----------------
    def to_string(self) -> str:
        buf = io.BytesIO()
        arrays = dict(feats=self.feats, thr_raw=self.thr_raw,
                      leaf_values=self.leaf_values, gains=self.gains,
                      covers=self.covers)
        if self.is_linear:
            arrays["lin_coefs"] = self.linear["coefs"]
            arrays["lin_pf"] = self.linear["pf"]
        np.savez_compressed(buf, **arrays)
        meta = {"depth": self.depth, "n_features": self.n_features,
                "objective": self.objective, "base_score": self.base_score,
                "num_class": self.num_class,
                "best_iteration": self.best_iteration,
                "arrays": base64.b64encode(buf.getvalue()).decode("ascii")}
        if self.cat_encoder is not None:
            meta["cat_encoder"] = self.cat_encoder.to_dict()
        if self.fit_params is not None:
            meta["fit_params"] = self.fit_params
        return json.dumps(meta)

    @staticmethod
    def from_string(s: str) -> "Booster":
        meta = json.loads(s)
        buf = io.BytesIO(base64.b64decode(meta["arrays"]))
        with np.load(buf) as z:
            arrays = {k: z[k] for k in z.files}
        b = Booster(meta["depth"], meta["n_features"], meta["objective"],
                    meta["base_score"], meta["num_class"],
                    arrays["feats"], arrays["thr_raw"],
                    arrays["leaf_values"], arrays["gains"],
                    arrays["covers"], meta["best_iteration"])
        if "lin_coefs" in arrays:
            b._lin_base = {"coefs": arrays["lin_coefs"],
                           "pf": arrays["lin_pf"]}
        if "cat_encoder" in meta:
            from .categorical import CategoricalEncoder
            b.cat_encoder = CategoricalEncoder.from_dict(meta["cat_encoder"])
        b.fit_params = meta.get("fit_params")
        return b
