"""Exclusive Feature Bundling (EFB) — sparse histogram acceleration.

LightGBM's second headline optimization (with GOSS): features that are
(almost) never non-default simultaneously share one histogram column, so
per-level histogram cost drops from O(n × F) to O(n × n_bundles). The
reference exposes it through LightGBM's ``enable_bundle`` /
``max_conflict_rate`` passthrough params (rendered by
``params/TrainParams.scala:10-100``); the algorithm is native C++ there.

TPU-first reformulation (no ragged structures, no per-row pointer chases):

* **encode**: every feature belongs to exactly one bundle; member ``f``
  gets a contiguous slot ``[offset_f, offset_f + width_f)`` in its
  bundle's bin space, bundle bin ``offset_f + bin_f`` when ``f`` is
  non-default, and all-default rows encode to bundle bin 0. The bundled
  matrix is the ONLY per-row artifact — (n, n_bundles) uint16 instead of
  (n, F) uint8.
* **histogram**: one scatter-add over bundle bins per level (the existing
  kernel, just narrower). The win is in the per-column passes and the
  bin-matrix traffic (HBM reads and host→device transfer shrink from
  n×F to n×n_bundles bytes); the psum payload is ≈ conserved — total
  bins are invariant (n_bundles × span ≈ F × B) — so data-parallel comm
  neither shrinks nor grows beyond span padding.
* **debundle**: per-feature histograms are reconstructed EXACTLY by a
  static gather plus the default-bin subtraction trick (default-bin
  stats = node totals − the feature's non-default stats), so split
  finding, feature_mask, PV-Tree voting, and thresholds all operate in
  original-feature space, unchanged.
* **route**: row partitioning decodes a row's original-feature bin from
  its bundle column with two gathers and a ``where`` — no decode tables
  on the hot path beyond three (F,)-shaped arrays.

With ``max_conflict_rate=0`` (the default, matching LightGBM) bundling is
lossless: trees are bit-identical to unbundled training. A positive rate
allows bundles whose members collide on at most ``rate × n`` rows; a
colliding row keeps the LAST member's value (its other features read as
default for that row) — the standard EFB approximation.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from .binning import BinMapper, is_sparse

__all__ = ["FeatureBundler", "plan_bundles"]


def _csc_fingerprint(X) -> tuple:
    """Cheap identity check for reusing fit-time binning at transform time:
    shape + nnz + head/tail samples of the value and index buffers. Not a
    cryptographic guarantee — a collision needs a same-shape, same-nnz
    matrix agreeing on 64 sampled entries, at which point the caller is
    actively trying to be wrong."""
    d, i = X.data, X.indices
    return (X.shape, X.nnz,
            d[:32].tobytes(), d[-32:].tobytes(),
            i[:32].tobytes(), i[-32:].tobytes())


def plan_bundles(nondefault_rows: List[np.ndarray], n_rows: int,
                 widths: np.ndarray, max_conflict_rate: float = 0.0,
                 max_bundle_bins: int = 4096) -> List[List[int]]:
    """Greedy first-fit bundling (the EFB paper's graph-coloring heuristic).

    ``nondefault_rows[f]`` = sorted row indices where feature ``f`` is
    non-default; ``widths[f]`` = f's bin count. Features are visited in
    descending non-default count (densest first — they are hardest to
    place); a feature joins the first bundle where (a) added conflicts
    stay within the bundle's remaining budget and (b) the bin span stays
    under ``max_bundle_bins`` (a huge bundle would pad every bundle's
    histogram to its width — ragged-to-static cost).
    """
    F = len(nondefault_rows)
    budget = int(max_conflict_rate * n_rows)
    order = np.argsort([-len(r) for r in nondefault_rows], kind="stable")
    bundles: List[List[int]] = []
    occupied: List[np.ndarray] = []     # bool (n_rows,) per bundle
    remaining: List[int] = []
    span: List[int] = []
    for f in order:
        rows = nondefault_rows[f]
        placed = False
        for b in range(len(bundles)):
            if span[b] + int(widths[f]) > max_bundle_bins:
                continue
            conflicts = int(occupied[b][rows].sum()) if len(rows) else 0
            if conflicts <= remaining[b]:
                bundles[b].append(int(f))
                occupied[b][rows] = True
                remaining[b] -= conflicts
                span[b] += int(widths[f])
                placed = True
                break
        if not placed:
            bundles.append([int(f)])
            occ = np.zeros(n_rows, dtype=bool)
            occ[rows] = True
            occupied.append(occ)
            remaining.append(budget)
            span.append(1 + int(widths[f]))
    return bundles


class FeatureBundler:
    """Plans bundles from a fitted :class:`BinMapper` + sparse matrix and
    encodes the bundled bin matrix.

    Tables (all (F,) int32, consumed by ``trees.build_tree``):
      ``bundle_of`` — bundle index per original feature;
      ``offset_of`` — the feature's slot offset inside its bundle;
      ``width_of``  — the feature's bin count (bins land in
      ``[offset, offset + width)``);
      ``zero_bin``  — the feature's default (zero-value) bin.
    """

    def __init__(self, max_conflict_rate: float = 0.0,
                 max_bundle_bins: int = 4096,
                 plan_sample_cnt: int = 100_000, seed: int = 0):
        self.max_conflict_rate = float(max_conflict_rate)
        self.max_bundle_bins = int(max_bundle_bins)
        self.plan_sample_cnt = int(plan_sample_cnt)
        self.seed = seed
        self.bundles: List[List[int]] = []
        self.bundle_of: Optional[np.ndarray] = None
        self.offset_of: Optional[np.ndarray] = None
        self.width_of: Optional[np.ndarray] = None
        self.zero_bin: Optional[np.ndarray] = None
        self.n_bundle_bins: int = 0
        self._bin_cache = None          # (fingerprint, [(rows, bins)] per f)

    # -- planning ------------------------------------------------------------
    def fit(self, X, mapper: BinMapper) -> "FeatureBundler":
        if not is_sparse(X):
            raise ValueError("FeatureBundler.fit expects a scipy sparse "
                             "matrix (bundling is a sparse-data device)")
        X = X.tocsc()
        n, F = X.shape
        widths = np.array([1 + len(b) for b in mapper.upper_bounds],
                          dtype=np.int64)     # bins incl. the missing bin 0
        self.zero_bin = np.array(
            [int(np.searchsorted(b, 0.0, side="left")) + 1
             for b in mapper.upper_bounds], dtype=np.int32)
        nondefault: List[np.ndarray] = []
        cache: List[tuple] = []
        for j in range(F):
            lo, hi = X.indptr[j], X.indptr[j + 1]
            vals = X.data[lo:hi]
            rows = X.indices[lo:hi]
            bins = np.searchsorted(mapper.upper_bounds[j], vals,
                                   side="left") + 1
            if vals.dtype.kind == "f":
                bins = np.where(np.isnan(vals), 0, bins)
            # stored values that bin into the zero bin ARE default
            keep = bins != self.zero_bin[j]
            nondefault.append(np.sort(rows[keep]))
            cache.append((rows[keep], bins[keep]))
        # binning every stored value is the expensive part of both fit and
        # transform — keep it for transform (same X → no recompute)
        self._bin_cache = (_csc_fingerprint(X), cache)
        # conflict counting runs on a bounded row sample: exact counting
        # keeps an O(n)-bool occupancy map per bundle, which at HIGGS-scale
        # n dwarfs the sparse data itself (LightGBM samples here too); a
        # sampled miss can bundle a pair conflicting slightly above budget
        # — the standard EFB approximation
        if n > self.plan_sample_cnt:
            rng = np.random.default_rng(self.seed)
            sample = np.sort(rng.choice(n, self.plan_sample_cnt,
                                        replace=False))
            plan_rows = []
            for r in nondefault:
                in_sample = r[np.isin(r, sample, assume_unique=True)]
                plan_rows.append(np.searchsorted(sample, in_sample))
            plan_n = self.plan_sample_cnt
        else:
            plan_rows, plan_n = nondefault, n
        self.bundles = plan_bundles(plan_rows, plan_n, widths,
                                    self.max_conflict_rate,
                                    self.max_bundle_bins)
        self.bundle_of = np.zeros(F, dtype=np.int32)
        self.offset_of = np.zeros(F, dtype=np.int32)
        self.width_of = widths.astype(np.int32)
        spans = []
        for b, members in enumerate(self.bundles):
            off = 1                       # slot 0 = the all-default bin
            for f in members:
                self.bundle_of[f] = b
                self.offset_of[f] = off
                off += int(widths[f])
            spans.append(off)
        self.n_bundle_bins = int(max(spans)) if spans else 1
        return self

    @property
    def n_bundles(self) -> int:
        return len(self.bundles)

    def worthwhile(self, F: int) -> bool:
        """Bundling pays when it actually shrinks the histogram work; a
        near-1:1 plan would only add the debundle gather."""
        return self.n_bundles <= max(1, int(0.75 * F))

    # -- encoding ------------------------------------------------------------
    def transform(self, X, mapper: BinMapper) -> np.ndarray:
        """Sparse matrix → (n, n_bundles) bundled bin matrix.

        Cost ∝ nnz: per column, binned non-default entries scatter into
        the member's slot range; conflict rows resolve last-member-wins
        (members are visited in bundle order, so the resolution is
        deterministic)."""
        if not is_sparse(X):
            raise ValueError("FeatureBundler.transform expects sparse input")
        X = X.tocsc()
        n, F = X.shape
        cached = (self._bin_cache[1]
                  if self._bin_cache is not None
                  and self._bin_cache[0] == _csc_fingerprint(X) else None)
        dtype = np.uint16 if self.n_bundle_bins > 256 else np.uint8
        out = np.zeros((n, self.n_bundles), dtype=dtype)
        for b, members in enumerate(self.bundles):
            for f in members:
                if cached is not None:
                    rows_nd, bins_nd = cached[f]
                else:
                    lo, hi = X.indptr[f], X.indptr[f + 1]
                    vals = X.data[lo:hi]
                    rows = X.indices[lo:hi]
                    bins = np.searchsorted(mapper.upper_bounds[f], vals,
                                           side="left") + 1
                    if vals.dtype.kind == "f":
                        bins = np.where(np.isnan(vals), 0, bins)
                    keep = bins != self.zero_bin[f]
                    rows_nd, bins_nd = rows[keep], bins[keep]
                out[rows_nd, b] = (self.offset_of[f]
                                   + bins_nd).astype(dtype)
        return out
